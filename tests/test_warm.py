"""Temporal warm-start tests (ISSUE 11, DESIGN.md "Temporal warm-start").

Unit tier (fake executor / store-level, no jax): the prior-flow
lifecycle — set only via the guarded engine writeback, handed to warm
steps, DROPPED on tombstone re-prime and mid-session rebucket so a
410-resume or resolution change dispatches cold, never refines against
stale/mis-sized flow; warm batching (a warm step and a cold request
never share a flush); `SessionConfig` round-trip + unknown-`warm_start`
-typo rejection at every nesting level; observability surfacing
(stats -> /metrics -> heartbeat/tail -> analyze merge, with the per-key
histogram merge pinned alongside the new counters).

Real-model tier: warm-path output deterministic and bit-stable across
repeated dispatches AND across engines (seeded refinement init);
`warm_start=false` flows bitwise-identical to the pairwise walk (the
PR 10 contract, unchanged); `epe_vs_cold` within the quality gate on a
coherent walk; `warmup --serve` report covers the bucket x tier x
{cold, warm} lattice.

Slow tier: the PR 7-style zero-recompile acceptance extended to the
warm axis — after `warmup --serve` on a warm-enabled config, a cold
engine's first WARM request loads its executable (report-driven:
misses <= skipped).
"""

import dataclasses
import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from conftest import wait_for_listen

from deepof_tpu.core.config import config_from_dict, get_config
from deepof_tpu.serve.engine import (InferenceEngine, ServeError,
                                     make_fake_forward)
from deepof_tpu.serve.session import SessionExpired, SessionStore

# ----------------------------------------------------------- helpers


def _cfg(max_batch=4, timeout_ms=5.0, buckets=(), image_size=(32, 64),
         log_dir="/tmp/deepof_warm_test", session_kw=None, **serve_kw):
    cfg = get_config("flyingchairs")
    session = dataclasses.replace(cfg.serve.session, warm_start=True)
    if session_kw:
        session = dataclasses.replace(session, **session_kw)
    return cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=image_size, gt_size=image_size),
        serve=dataclasses.replace(cfg.serve, max_batch=max_batch,
                                  batch_timeout_ms=timeout_ms,
                                  buckets=buckets, session=session,
                                  **serve_kw),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6), log_dir=log_dir))


def _img(rng, hw=(30, 60)):
    return rng.randint(1, 255, (*hw, 3), dtype=np.uint8)


_SERVE_BENCH = None


def _serve_bench():
    """tools/serve_bench.py, loaded once: the unit tier reuses the
    benchmark's OWN helpers (coherent walk, real-model init) so it
    measures exactly the workload the warm bench pins."""
    global _SERVE_BENCH
    if _SERVE_BENCH is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "serve_bench.py")
        spec = importlib.util.spec_from_file_location("serve_bench_warm",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _SERVE_BENCH = mod
    return _SERVE_BENCH


def _coherent(rng, n, hw=(30, 60)):
    return _serve_bench()._coherent_walk(rng, hw, n)


def _row(rng, hw=(4, 4)):
    return rng.rand(*hw, 3).astype(np.float32)


# ------------------------------------------------------ SessionStore


def test_store_prior_flow_lifecycle(rng):
    """The prior is None until set_flow lands, rides later steps, and is
    dropped by rebucket; set_flow is guarded on liveness, bucket, AND
    prime-generation epoch."""
    store = SessionStore(max_sessions=4, ttl_s=0, sweep_s=0)
    store.advance("v", _row(rng), (4, 4), (4, 4), "f32")
    kind, _, prior, epoch, _ = store.advance("v", _row(rng), (4, 4),
                                             (4, 4), "f32")
    assert kind == "step" and prior is None  # first step: nothing cached

    flow = np.ones((2, 2, 2), np.float32)
    assert store.set_flow("v", flow, (4, 4), epoch) is True
    out = store.advance("v", _row(rng), (4, 4), (4, 4), "f32")
    assert out[0] == "step" and np.array_equal(out[2], flow)

    # wrong-bucket writeback (a rebucket raced the dispatch): dropped
    assert store.set_flow("v", flow, (8, 8), epoch) is False
    # wrong-generation writeback: dropped
    assert store.set_flow("v", flow, (4, 4), epoch + 99) is False
    # dead-session writeback: dropped
    assert store.set_flow("ghost", flow, (4, 4), epoch) is False

    # mid-session rebucket re-primes AND drops the cached flow
    store.set_flow("v", flow, (4, 4), epoch)
    kind, s = store.advance("v", _row(rng, (8, 8)), (8, 8), (8, 8), "f32")
    assert kind == "primed" and s.flow is None
    out = store.advance("v", _row(rng, (8, 8)), (8, 8), (8, 8), "f32")
    assert out[0] == "step" and out[2] is None  # cold again, by construction
    # a straggler writeback from the OLD generation (same sid, the old
    # bucket) cannot land on the rebucketed session
    assert store.set_flow("v", flow, (4, 4), epoch) is False
    store.close()


def test_store_tombstone_resume_drops_prior_and_rejects_stragglers(rng):
    """A TTL-expired session's re-prime (the 410-resume) starts with no
    prior — the resumed session's first step must dispatch cold — and a
    dispatch that was in flight ACROSS the expiry cannot write its flow
    into the resumed session (same sid, same bucket: only the epoch
    tells them apart)."""
    store = SessionStore(max_sessions=4, ttl_s=0.15, sweep_s=0)
    store.advance("v", _row(rng), (4, 4), (4, 4), "f32")
    out = store.advance("v", _row(rng), (4, 4), (4, 4), "f32")
    old_epoch = out[3]
    store.set_flow("v", np.ones((2, 2, 2), np.float32), (4, 4), old_epoch)
    time.sleep(0.25)
    with pytest.raises(SessionExpired):
        store.advance("v", _row(rng), (4, 4), (4, 4), "f32")
    kind, s = store.advance("v", _row(rng), (4, 4), (4, 4), "f32")
    assert kind == "primed" and s.flow is None
    assert store.stats()["serve_sessions_resumed"] == 1
    # the pre-expiry dispatch resolves late: same sid, same bucket —
    # dropped on the epoch guard, so the resumed session stays cold
    assert store.set_flow("v", np.ones((2, 2, 2), np.float32),
                          (4, 4), old_epoch) is False
    out = store.advance("v", _row(rng), (4, 4), (4, 4), "f32")
    assert out[0] == "step" and out[2] is None
    store.close()


# ------------------------------------------------- engine (fake exec)


def test_engine_warm_fake_counters_parity_and_off_schema(rng):
    """Fake-executor warm engine: the executor is warm-blind, so warm
    flows are bitwise the cold engine's AND the pairwise path's — the
    warm axis changes dispatch routing and bookkeeping, never numerics,
    for custom executors. Counters: 1 cold fallback then warm steps;
    the `warm` response flag appears ONLY under the toggle (the
    warm_start=false response schema is the PR 10 one, unchanged)."""
    frames = [_img(rng) for _ in range(6)]
    with InferenceEngine(_cfg(), forward_fn=make_fake_forward(1.0)) as eng:
        pairwise = [eng.submit(a, b).result(30)["flow"]
                    for a, b in zip(frames, frames[1:])]
        eng.submit_next("vid", frames[0]).result(30)
        streamed = [eng.submit_next("vid", f).result(30)
                    for f in frames[1:]]
        stats = eng.stats()
    assert [st["warm"] for st in streamed] == [False, True, True, True, True]
    for i, (pw, st) in enumerate(zip(pairwise, streamed)):
        assert np.array_equal(pw, st["flow"]), f"pair {i} diverged"
    assert stats["serve_sessions_warm_steps"] == 4
    assert stats["serve_sessions_cold_fallbacks"] == 1
    assert stats["serve_sessions_warm_start"] is True
    assert stats["serve_warm_splits"] >= 0  # schema: the key exists

    cfg_off = _cfg(session_kw=dict(warm_start=False))
    with InferenceEngine(cfg_off, forward_fn=make_fake_forward(1.0)) as eng:
        eng.submit_next("vid", frames[0]).result(30)
        off = [eng.submit_next("vid", f).result(30) for f in frames[1:]]
        stats = eng.stats()
    for i, (pw, st) in enumerate(zip(pairwise, off)):
        assert np.array_equal(pw, st["flow"]), f"off pair {i} diverged"
    assert all("warm" not in st for st in off)  # PR 10 schema exactly
    assert stats["serve_sessions_warm_steps"] == 0
    assert stats["serve_sessions_cold_fallbacks"] == 0
    assert stats["serve_sessions_warm_start"] is False


def test_engine_warm_step_never_shares_a_flush_with_cold(rng):
    """A warm step and a cold request queued together split the batch
    (the tier-switch contract extended to the mode axis): counted as
    serve_warm_splits, and both still resolve."""
    cfg = _cfg(max_batch=4, timeout_ms=60.0)
    frames = [_img(rng) for _ in range(3)]
    with InferenceEngine(cfg, forward_fn=make_fake_forward(25.0)) as eng:
        eng.submit_next("v", frames[0]).result(30)
        eng.submit_next("v", frames[1]).result(30)  # seeds the prior
        # the next step is warm; enqueue a cold pairwise request right
        # behind it inside the batching window — same bucket, same tier,
        # different mode: must flush separately
        f_warm = eng.submit_next("v", frames[2])
        f_cold = eng.submit(frames[1], frames[2])
        assert f_warm.result(30)["warm"] is True
        assert "flow" in f_cold.result(30)
        stats = eng.stats()
    assert stats["serve_warm_splits"] >= 1, stats
    assert stats["serve_sessions_warm_steps"] == 1


def test_engine_warm_rebucket_and_expiry_fall_back_cold(rng):
    """Engine-level pins of the two drop paths: a mid-session rebucket
    and a tombstone re-prime each force the NEXT step cold (counted),
    even though earlier steps were warming."""
    cfg = _cfg(buckets=((32, 64), (64, 64)),
               session_kw=dict(ttl_s=0.2, sweep_s=0.0))
    small = [_img(rng, (30, 60)) for _ in range(3)]
    big = [_img(rng, (60, 60)) for _ in range(3)]
    with InferenceEngine(cfg, forward_fn=make_fake_forward(1.0)) as eng:
        eng.submit_next("v", small[0]).result(30)
        assert eng.submit_next("v", small[1]).result(30)["warm"] is False
        assert eng.submit_next("v", small[2]).result(30)["warm"] is True
        # resolution change: re-prime in place, prior dropped
        assert eng.submit_next("v", big[0]).result(30)["primed"] is True
        r = eng.submit_next("v", big[1]).result(30)
        assert r["warm"] is False  # cold fallback after rebucket
        assert eng.submit_next("v", big[2]).result(30)["warm"] is True
        stats = eng.stats()
        assert stats["serve_sessions_cold_fallbacks"] == 2
        assert stats["serve_sessions_warm_steps"] == 2

        time.sleep(0.4)  # TTL: tombstone, then 410-style resume
        with pytest.raises(ServeError) as exc:
            eng.submit_next("v", big[0]).result(30)
        assert exc.value.code == "session_expired"
        assert eng.submit_next("v", big[0]).result(30)["primed"] is True
        r = eng.submit_next("v", big[1]).result(30)
        assert r["warm"] is False  # resumed session starts cold
        stats = eng.stats()
        assert stats["serve_sessions_cold_fallbacks"] == 3
        assert stats["serve_sessions_resumed"] == 1


# ------------------------------------------------------------ config


def test_warm_config_round_trip_and_typo_rejection_every_level():
    """The parent->replica handoff covers the warm knobs, and an
    unknown `warm_start` typo is rejected loudly at EVERY nesting
    level — a typo'd toggle must never silently stay off."""
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(serve=dataclasses.replace(
        cfg.serve, session=dataclasses.replace(
            cfg.serve.session, warm_start=True, warm_width=0.25)))
    restored = config_from_dict(json.loads(json.dumps(
        dataclasses.asdict(cfg))))
    assert restored == cfg
    assert restored.serve.session.warm_start is True
    assert restored.serve.session.warm_width == 0.25
    # typo rejection at every nesting level ("warm_stat" /
    # "session_warm_start" / top-level "warm_start") moved to the
    # registry-driven whole-tree walk in test_lint.py, which keeps
    # these assertions as parity pins


# ----------------------------------------------------- observability


def test_warm_counters_on_metrics_healthz_tail_and_analyze(rng, tmp_path):
    """The warm ledger rides every existing surface: engine stats ->
    /healthz + Prometheus /metrics (generic render), heartbeat -> tail,
    and analyze's merged child aggregation (counters sum; the per-key
    histogram merge keeps working with the new keys present)."""
    import http.client

    from deepof_tpu.analyze import aggregate_processes, tail_summary
    from deepof_tpu.obs.export import LatencyHistogram, parse_prometheus
    from deepof_tpu.serve.server import build_server

    cfg = _cfg(port=0, log_dir=str(tmp_path))
    frames = [_img(rng) for _ in range(4)]
    eng = InferenceEngine(cfg, forward_fn=make_fake_forward(1.0))
    httpd = build_server(cfg, eng)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    wait_for_listen("127.0.0.1", port)
    try:
        eng.submit_next("v", frames[0]).result(30)
        for f in frames[1:]:
            eng.submit_next("v", f).result(30)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            conn.request("GET", "/metrics")
            text = conn.getresponse().read().decode()
        finally:
            conn.close()
        samples = parse_prometheus(text)
        assert samples["deepof_serve_sessions_warm_steps"] == 2.0
        assert samples["deepof_serve_sessions_cold_fallbacks"] == 1.0
        assert samples["deepof_serve_sessions_warm_start"] == 1.0
    finally:
        httpd.shutdown()
        httpd.server_close()
        eng.close()

    # tail: heartbeat carries the live block; analyze: children merge
    hist = LatencyHistogram()
    hist.observe(0.01)
    snap = hist.snapshot()
    (tmp_path / "metrics.jsonl").write_text(json.dumps(
        {"kind": "serve", "step": 0, "time": time.time(),
         "serve_requests": 3, "serve_responses": 3}) + "\n")
    (tmp_path / "heartbeat.json").write_text(json.dumps(
        {"time": time.time(), "step": 5, "wedged": False,
         "serve_sessions_warm_steps": 7,
         "serve_sessions_cold_fallbacks": 2}))
    out = tail_summary(str(tmp_path))
    assert out["serve"]["sessions_warm_steps"] == 7
    assert out["serve"]["sessions_cold_fallbacks"] == 2
    for i in range(2):
        d = tmp_path / f"replica-{i}"
        d.mkdir()
        (d / "metrics.jsonl").write_text(json.dumps(
            {"kind": "serve", "step": 0, "time": time.time(),
             "serve_sessions_warm_steps": 3,
             "serve_sessions_cold_fallbacks": 1,
             "serve_sessions_steps": 4,
             "serve_latency_hist": snap,
             "serve_session_latency_hist": snap}) + "\n")
    merged = aggregate_processes(str(tmp_path))["merged"]
    assert merged["sessions_warm_steps"] == 6
    assert merged["sessions_cold_fallbacks"] == 2
    # the per-key histogram merge still lands exactly with the new
    # counter keys present in the same records
    assert merged["latency_hist"]["count"] == 2
    assert merged["session_latency_hist"]["count"] == 2


# ------------------------------------------------- real-model quality


def _real_model_params(cfg):
    return _serve_bench()._real_model_params(cfg)


def test_warm_real_model_deterministic_bitstable_and_quality(rng):
    """Real flownet_s: (a) the warm() report covers the cold+warm mode
    lattice; (b) warm-path flows are bit-stable across repeated
    dispatches on one engine AND across engines (seeded refinement
    init); (c) `epe_vs_cold` on a coherent walk is inside the <= 0.5 px
    quality gate; (d) the warm_start=false walk stays bitwise the
    pairwise path's (the PR 10 parity pin, under the new code)."""
    cfg = _cfg(max_batch=2, timeout_ms=2.0)
    model_params = _real_model_params(cfg)
    frames = _coherent(np.random.RandomState(3), 5)

    def walk(engine, sid):
        engine.submit_next(sid, frames[0]).result(120)
        return [engine.submit_next(sid, f).result(120)
                for f in frames[1:]]

    with InferenceEngine(cfg, model_params=model_params) as eng:
        report = eng.warm()
        modes = {(tuple(b["bucket"]), b["tier"], b["mode"])
                 for b in report["buckets"]}
        assert modes == {((32, 64), "f32", "cold"),
                         ((32, 64), "f32", "warm")}
        a = walk(eng, "one")
        b = walk(eng, "two")  # repeated dispatches, same engine
        for i, (x, y) in enumerate(zip(a, b)):
            assert np.array_equal(x["flow"], y["flow"]), f"step {i}"
        assert [r["warm"] for r in a] == [False, True, True, True]

    with InferenceEngine(cfg, model_params=model_params) as eng2:
        eng2.warm()
        c = walk(eng2, "three")  # fresh engine: seeded init, same bits
    for i, (x, y) in enumerate(zip(a, c)):
        assert np.array_equal(x["flow"], y["flow"]), f"engine step {i}"

    cfg_off = _cfg(max_batch=2, timeout_ms=2.0,
                   session_kw=dict(warm_start=False))
    with InferenceEngine(cfg_off, model_params=model_params) as eng3:
        eng3.warm()
        cold = walk(eng3, "four")
        pairwise = [eng3.submit(p, n).result(120)["flow"]
                    for p, n in zip(frames, frames[1:])]
    for i, (st, pw) in enumerate(zip(cold, pairwise)):
        assert np.array_equal(st["flow"], pw), f"pairwise step {i}"
        assert "warm" not in st
    # quality gate: warm flows vs the cold walk's on the same frames
    epes = [float(np.mean(np.sqrt(np.sum((x["flow"] - y["flow"]) ** 2,
                                         -1))))
            for x, y in zip(a, cold)]
    assert max(epes) <= 0.5, epes
    # the first warm-walk step fell back cold: identical bits
    assert np.array_equal(a[0]["flow"], cold[0]["flow"])


def test_warmup_serve_report_covers_warm_lattice():
    """`warmup --serve` on a warm-enabled config reports the full
    bucket x tier x {cold, warm} lattice in engine order (report
    structure only — the persistence pin is the slow test below)."""
    from deepof_tpu.train import warmup

    cfg = _cfg(max_batch=2, timeout_ms=2.0)
    res = warmup.warmup_serve(cfg)
    assert res["modes"] == ["cold", "warm"]
    assert [(tuple(b["bucket"]), b["tier"], b["mode"])
            for b in res["buckets"]] == \
        [((32, 64), "f32", "cold"), ((32, 64), "f32", "warm")]
    for b in res["buckets"]:
        assert b["status"] in ("persisted", "hit", "skipped")


# ------------------------------------------------- slow: zero-recompile


@pytest.mark.slow
def test_warmup_serve_then_first_warm_request_compiles_nothing(tmp_path):
    """The PR 7 zero-recompile acceptance extended to the warm axis:
    after `warmup --serve` lowers the bucket x tier x {cold, warm}
    lattice into the persistent cache, a cold engine's first WARM
    request (prime -> cold-fallback step -> warm step) loads its
    executables — report-driven, misses <= skipped, exactly the PR 7
    style (a sub-1 s compile legitimately recompiles next process)."""
    import jax
    import jax.numpy as jnp

    from deepof_tpu.serve.engine import build_serve_model
    from deepof_tpu.train import warmup

    prev = jax.config.jax_compilation_cache_dir
    try:
        cfg = _cfg(max_batch=2, timeout_ms=40.0, buckets=((64, 64),),
                   image_size=(64, 64), log_dir=str(tmp_path / "run"))
        cfg = cfg.replace(model="inception_v3", width_mult=1.0,
                          train=dataclasses.replace(
                              cfg.train, compile_cache=True,
                              compile_cache_dir=str(tmp_path / "xla_cache")))

        r1 = warmup.warmup_serve(cfg)
        lattice = [((64, 64), "f32", "cold"), ((64, 64), "f32", "warm")]
        assert [(tuple(b["bucket"]), b["tier"], b["mode"])
                for b in r1["buckets"]] == lattice
        assert r1["cache"]["misses"] >= len(lattice)
        persisted = {(tuple(b["bucket"]), b["tier"], b["mode"])
                     for b in r1["buckets"] if b["persisted"]}
        if not persisted:
            pytest.skip("no lattice entry cleared the 1 s persistence "
                        "floor on this host — nothing to pin")

        jax.clear_caches()  # simulate a cold serving process
        model = build_serve_model(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 64, 64, 6)))["params"]
        rng = np.random.RandomState(0)
        frames = [rng.randint(1, 255, (60, 60, 3), dtype=np.uint8)
                  for _ in range(3)]
        with InferenceEngine(cfg, model_params=(model, params)) as eng:
            with warmup.cache_delta() as d:
                eng.submit_next("v", frames[0]).result(600)
                step1 = eng.submit_next("v", frames[1]).result(600)
                step2 = eng.submit_next("v", frames[2]).result(600)
        assert step1["warm"] is False and step2["warm"] is True
        assert np.isfinite(step2["flow"]).all()
        delta = d.stats()
        assert delta["requests"] >= len(lattice)
        assert delta["hits"] >= len(persisted), \
            "a persisted lattice entry recompiled — warmup_serve's " \
            "warm lowering drifted from the engine's"
        assert delta["misses"] <= len(lattice) - len(persisted), \
            f"more recompiles ({delta['misses']}) than skipped entries " \
            f"({len(lattice) - len(persisted)})"
    finally:
        warmup.enable_compile_cache(prev)
