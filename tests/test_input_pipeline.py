"""Multi-worker host input pipeline (`data/pipeline.py`): deterministic
ordering, bounded reorder buffer, concurrency, observability — and the
ISSUE 2 acceptance pin: under an injected per-image decode delay, 4
workers beat the single-thread path >= 2x end-to-end while delivering a
bit-identical batch stream. Pure host-side mechanics plus one tiny jit
step — fast tier (pattern of test_pipeline.py).
"""

import hashlib
import threading
import time

import numpy as np
import pytest

from deepof_tpu.core.config import DataConfig
from deepof_tpu.data.datasets import SyntheticData, _DecodedCache
from deepof_tpu.data.pipeline import (InputPipeline, derive_batch_rng,
                                      resolve_num_workers)
from deepof_tpu.data.prefetch import Prefetcher


def _digest(batch: dict) -> str:
    h = hashlib.sha1()
    for k in sorted(batch):
        h.update(k.encode())
        h.update(np.ascontiguousarray(np.asarray(batch[k])).tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------- seeding

def test_derive_batch_rng_deterministic_and_distinct():
    a = derive_batch_rng(7, 3).randint(0, 2**31, 8)
    a2 = derive_batch_rng(7, 3).randint(0, 2**31, 8)
    b = derive_batch_rng(7, 4).randint(0, 2**31, 8)
    c = derive_batch_rng(8, 3).randint(0, 2**31, 8)
    np.testing.assert_array_equal(a, a2)  # pure in (base, index)
    assert not np.array_equal(a, b)  # index decorrelates
    assert not np.array_equal(a, c)  # base decorrelates
    # array base seeds (the loop's data_stream_seed) work and differ
    d = derive_batch_rng(np.array([7, 0], np.uint32), 3).randint(0, 2**31, 8)
    assert not np.array_equal(a, d)
    # 64-bit indices AND base seeds fold in losslessly (no truncation)
    derive_batch_rng(7, 2**40 + 5).randint(0, 10)
    hi = derive_batch_rng(2**32, 3).randint(0, 2**31, 8)
    lo = derive_batch_rng(0, 3).randint(0, 2**31, 8)
    assert not np.array_equal(hi, lo)


def test_resolve_num_workers_auto_mode():
    """`data.num_workers = -1` (auto) sizes the pool to the host: 0 on
    <= 2 cores — BENCH_r06 measured workers=4 at 49.5 vs workers=0 at
    85.3 batches/s on a small host (thread contention, nothing to
    overlap) — else min(4, cores - 2). Explicit values pass through."""
    # explicit settings are never second-guessed
    for n in (0, 1, 3, 7):
        assert resolve_num_workers(n, cpu_count=1) == n
    # only -1 means auto: a typo'd negative is rejected loudly
    with pytest.raises(ValueError, match="-3"):
        resolve_num_workers(-3)
    # auto: small hosts get the inline path
    assert resolve_num_workers(-1, cpu_count=1) == 0
    assert resolve_num_workers(-1, cpu_count=2) == 0
    # auto: leave 2 cores for the runtime, cap at 4
    assert resolve_num_workers(-1, cpu_count=3) == 1
    assert resolve_num_workers(-1, cpu_count=4) == 2
    assert resolve_num_workers(-1, cpu_count=6) == 4
    assert resolve_num_workers(-1, cpu_count=64) == 4
    # the host-probe default resolves to SOMETHING valid
    assert resolve_num_workers(-1) >= 0
    # the pipeline itself honors auto (this container has <= 2 cores in
    # CI, but assert only the invariant: pool size == resolution)
    pipe = InputPipeline(lambda i: {"i": np.asarray([i])}, num_workers=-1)
    try:
        assert pipe.stats()["num_workers"] == resolve_num_workers(-1)
        assert pipe.get()["i"][0] == 0  # auto mode still delivers
    finally:
        pipe.close()


# ---------------------------------------------------- determinism contract

def _stream_hashes(num_workers: int, n: int = 8) -> list[str]:
    cfg = DataConfig(dataset="synthetic", image_size=(16, 16), batch_size=2)
    ds = SyntheticData(cfg)

    def assemble(i):
        return ds.sample_train(2, rng=derive_batch_rng(11, i))

    pipe = InputPipeline(assemble, num_workers=num_workers)
    try:
        return [_digest(pipe.get()) for _ in range(n)]
    finally:
        pipe.close()


def test_stream_bit_identical_across_worker_counts():
    """The contract: same config/seed => identical delivered stream for
    num_workers in {0, 1, 4} (hashes of the first K batches)."""
    h0 = _stream_hashes(0)
    h1 = _stream_hashes(1)
    h4 = _stream_hashes(4)
    assert h0 == h1 == h4
    assert len(set(h0)) == len(h0)  # and the batches genuinely differ


# -------------------------------------------------------------- concurrency

def test_workers_assemble_concurrently():
    """Injected-blocking proof (no wall-clock): batches 0 and 1 rendezvous
    at a 2-party barrier INSIDE make_batch — delivery can only complete if
    two workers were inside assembly at the same time."""
    barrier = threading.Barrier(2)
    met = {"ok": False}

    def make(i):
        if i < 2:
            barrier.wait(timeout=10.0)  # BrokenBarrierError on failure
            met["ok"] = True
        return {"i": np.asarray([i])}

    pipe = InputPipeline(make, num_workers=4)
    try:
        out = [int(pipe.get()["i"][0]) for _ in range(6)]
    finally:
        pipe.close()
    assert met["ok"]
    assert out == list(range(6))  # concurrent assembly, ordered delivery


def test_out_of_order_completion_delivers_in_order():
    """Early indices finish LAST; the reorder buffer must still deliver
    index order."""
    release = [threading.Event() for _ in range(4)]

    def make(i):
        if i < 4:
            release[i].wait(timeout=10.0)
        return {"i": np.asarray([i])}

    pipe = InputPipeline(make, num_workers=4)
    try:
        for ev in reversed(release):  # complete 3, 2, 1, 0
            ev.set()
            time.sleep(0.01)
        out = [int(pipe.get()["i"][0]) for _ in range(6)]
    finally:
        pipe.close()
    assert out == list(range(6))


def test_reorder_depth_bounds_claims():
    """Workers may never claim past next_out + reorder_depth: with the
    cursor's batch held back, at most `depth` assemblies start."""
    hold = threading.Event()
    started = []
    lock = threading.Lock()

    def make(i):
        with lock:
            started.append(i)
        if i == 0:
            hold.wait(timeout=10.0)
        return {"i": np.asarray([i])}

    pipe = InputPipeline(make, num_workers=4, reorder_depth=2)
    try:
        time.sleep(0.2)  # give eager workers every chance to overrun
        with lock:
            overrun = sorted(started)
        assert overrun == [0, 1]  # bound: claims < next_out(0) + depth(2)
        hold.set()
        out = [int(pipe.get()["i"][0]) for _ in range(4)]
    finally:
        pipe.close()
    assert out == list(range(4))


# ------------------------------------------------------------------ errors

@pytest.mark.parametrize("num_workers", [0, 2])
def test_pipeline_error_surfaces_on_get(num_workers):
    def boom(i):
        if i == 1:
            raise ValueError("decode failed")
        return {"i": np.asarray([i])}

    pipe = InputPipeline(boom, num_workers=num_workers)
    try:
        assert int(pipe.get()["i"][0]) == 0
        with pytest.raises(ValueError, match="decode failed"):
            pipe.get()
            pipe.get()  # workers=0 hits index 1 on the second call
    finally:
        pipe.close()


def test_close_is_idempotent_and_unblocks():
    pipe = InputPipeline(lambda i: {"i": np.asarray([i])}, num_workers=2)
    pipe.get()
    pipe.close()
    pipe.close()


# ----------------------------------------------------------- observability

def test_stats_schema_and_counters():
    def make(i):
        return {"x": np.zeros(4, np.float32)}

    pipe = InputPipeline(make, num_workers=2)
    try:
        for _ in range(5):
            pipe.get()
        s = pipe.stats()
    finally:
        pipe.close()
    for key in ("num_workers", "batches", "assemble_s", "assemble_s_mean",
                "queue_depth", "max_queue_depth", "waits", "wait_s",
                "worker_util"):
        assert key in s, key
    assert s["num_workers"] == 2
    assert s["batches"] >= 5
    assert s["max_queue_depth"] >= 1
    assert 0.0 <= s["worker_util"] <= 1.0


def test_decoded_cache_thread_safe_and_counted():
    """The shared decoded cache under worker-pool concurrency: counters
    add up, LRU state stays consistent, eviction accounting is exact."""
    decode_lock = threading.Lock()
    decodes = {"n": 0}

    def reader(path):
        with decode_lock:
            decodes["n"] += 1
        return np.ones((4, 4, 3), np.uint8)

    cache = _DecodedCache(True, reader, max_bytes=1 << 30)
    paths = [f"p{i}" for i in range(8)]
    n_threads, n_iter = 4, 200

    def hammer(seed):
        rs = np.random.RandomState(seed)
        for _ in range(n_iter):
            out = cache(paths[rs.randint(len(paths))])
            assert out.shape == (4, 4, 3)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = cache.stats()
    assert s["hits"] + s["misses"] == n_threads * n_iter
    assert s["misses"] >= len(paths)  # every path missed at least once
    assert s["misses"] == decodes["n"]  # one decode per counted miss
    assert s["entries"] == len(paths)
    assert s["evictions"] == 0

    # eviction accounting: capacity for ~2 entries of 48 bytes
    small = _DecodedCache(True, reader, max_bytes=100)
    for p in ("a", "b", "c", "d"):
        small(p)
    s2 = small.stats()
    assert s2["evictions"] == 2
    assert s2["bytes"] <= 100


# -------------------------------------------- ISSUE 2 acceptance criterion

class _SlowDecodeSynthetic(SyntheticData):
    """SyntheticData with an injected per-image decode delay (sleep-based:
    parallelizes under the GIL even on a 1-core host, so the test measures
    pipeline overlap, not machine core count). The delay is large relative
    to the real per-sample CPU work (~1 ms at 16x16), so scheduler noise
    cannot drown the signal."""

    DELAY_S = 0.02

    def _sample(self, seed, shift_bound=None):
        time.sleep(self.DELAY_S)
        return super()._sample(seed, shift_bound)


def _train_run(num_workers: int, n_batches: int = 8):
    """End-to-end synthetic training skeleton: pipeline -> prefetcher
    (device staging) -> jit train step -> metric fetch."""
    import jax
    import jax.numpy as jnp

    batch_size = 4
    cfg = DataConfig(dataset="synthetic", image_size=(16, 16),
                     batch_size=batch_size, num_workers=num_workers)
    ds = _SlowDecodeSynthetic(cfg, num_train=64)

    def assemble(i):
        return ds.sample_train(batch_size, rng=derive_batch_rng(5, i))

    pipe = InputPipeline(assemble, num_workers=num_workers)
    pf = Prefetcher(pipe.get, depth=2, stage=True)
    try:
        @jax.jit
        def train_step(p, batch):
            resid = batch["source"] / 255.0 - p[None]
            return p + 1e-2 * resid.mean(0), (resid ** 2).mean()

        params = jnp.zeros((16, 16, 3))
        hashes = []
        b = pf.get()  # warmup: compile outside the timed window
        hashes.append(_digest(b))
        params, loss = train_step(params, b)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(n_batches):
            b = pf.get()
            hashes.append(_digest(b))
            params, loss = train_step(params, b)
        final = float(loss)  # value fetch: the honest clock
        wall = time.perf_counter() - t0
        stats = pipe.stats()
    finally:
        pipe.close()
        pf.close()
    assert np.isfinite(final)
    return wall, hashes, stats


def test_multiworker_training_beats_single_thread_2x_and_matches():
    """Acceptance: with an injected per-image decode delay, num_workers=4
    end-to-end synthetic training throughput beats num_workers=0 by >= 2x,
    and the delivered batch stream is bit-identical across worker counts.

    Margins: per batch = 4 images x 20 ms = 80 ms serial assembly; over
    the 8-batch timed window the single-thread path is assembly-gated at
    ~1 batch/80 ms even after the prefetch queue's head start, while 4
    workers sustain ~1 batch/20 ms and enter the window with the reorder
    buffer primed — expected gap well past 3x, so >= 2x holds with slack
    (the injected delay sleeps rather than burning CPU, so 1-core hosts
    parallelize it). Determinism is asserted on EVERY attempt; the
    wall-clock ratio gets one bounded retry — a single descheduling spike
    on a saturated CI host must not fail the suite, two in a row is a
    real regression."""
    last = None
    for _ in range(2):
        wall0, h0, _ = _train_run(num_workers=0)
        wall4, h4, stats4 = _train_run(num_workers=4)
        assert h0 == h4  # bit-identical stream, every attempt
        if wall0 / wall4 >= 2.0:
            break
        last = (wall0, wall4, stats4)
    else:
        pytest.fail("num_workers=4 not >= 2x over single-thread in two "
                    f"attempts: wall0={last[0]:.3f}s wall4={last[1]:.3f}s "
                    f"pipeline stats={last[2]}")


# --------------------------------------------------- bench.py data mode

def test_data_bench_schema_and_throughput():
    """Tier-1 smoke for the data-only bench: runs on SyntheticData with a
    worker pool and emits the throughput/counter schema — so the
    observability surface can't silently rot."""
    import json

    import bench

    res = bench.data_bench(num_workers=2, batch=2, image_size=(16, 16),
                           batches=4)
    json.dumps(res)  # one JSON line, by construction
    for key in ("metric", "value", "unit", "mb_per_sec", "bytes_per_batch",
                "batches", "batch", "image_size", "dataset", "num_workers",
                "assemble_s_mean", "queue_depth", "max_queue_depth",
                "waits", "wait_s", "worker_util", "decode_cache_hits",
                "decode_cache_misses", "decode_cache_evictions"):
        assert key in res, key
    assert res["metric"] == bench.DATA_METRIC
    assert res["unit"] == bench.DATA_UNIT
    assert res["value"] > 0.0
    assert res["mb_per_sec"] > 0.0
    assert res["num_workers"] == 2
    assert res["batches"] == 4


def test_data_bench_deterministic_across_worker_counts():
    """The bench path inherits the pipeline contract: worker count is a
    throughput knob, never a stream change (value aside)."""
    import bench

    a = bench.data_bench(num_workers=0, batch=2, image_size=(16, 16),
                         batches=3)
    b = bench.data_bench(num_workers=3, batch=2, image_size=(16, 16),
                         batches=3)
    assert a["bytes_per_batch"] == b["bytes_per_batch"]
    assert a["decode_cache_misses"] == b["decode_cache_misses"] == 0
