"""Model-zoo tests: pyramid shapes/scales, bilinear deconv init, shared
siamese weights, two-stream outputs, registry, param counting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepof_tpu.models import (
    FlowNetS,
    VGG16Flow,
    InceptionV3Flow,
    FlowNetC,
    STSingle,
    STBaseline,
    UCF101Spatial,
    build_model,
    count_params,
    bilinear_kernel_init,
)

pytestmark = pytest.mark.slow  # full-model compiles; see pytest.ini
H, W = 64, 128  # divisible by 64


def _init_apply(model, x, train=None):
    kw = {} if train is None else {"train": train}
    variables = model.init(jax.random.PRNGKey(0), x, **kw)
    out = model.apply(variables, x, **kw)
    return variables, out


def test_flownet_s_pyramid():
    model = FlowNetS()
    x = jnp.zeros((2, H, W, 6))
    variables, flows = _init_apply(model, x)
    assert len(flows) == 6 and len(model.flow_scales) == 6
    assert model.flow_scales[0] == 10.0 and model.flow_scales[-1] == 0.3125
    # finest head at 1/2 resolution, halving per level
    for k, f in enumerate(flows):
        assert f.shape == (2, H >> (k + 1), W >> (k + 1), 2), (k, f.shape)
    n_params = count_params(variables["params"])
    assert 30e6 < n_params < 50e6  # FlowNet-S class size (~38M)


def test_flownet_s_multiframe_channels():
    model = FlowNetS(flow_channels=18)  # T=10 volume
    x = jnp.zeros((1, H, W, 30))
    _, flows = _init_apply(model, x)
    assert all(f.shape[-1] == 18 for f in flows)


def test_flownet_s_width_mult_thin_variant():
    """width_mult scales channels, not topology: same pyramid shapes and
    flow semantics, ~width_mult^2 of the parameters (the knob the slow
    tier's wiring tests rely on for cheap full-train-step compute)."""
    model = FlowNetS(width_mult=0.25)
    x = jnp.zeros((2, H, W, 6))
    variables, flows = _init_apply(model, x)
    assert len(flows) == 6
    for k, f in enumerate(flows):
        assert f.shape == (2, H >> (k + 1), W >> (k + 1), 2), (k, f.shape)
    n_thin = count_params(variables["params"])
    assert n_thin < 4e6  # ~38M * 0.0625 plus floor-of-8 layers


def test_vgg16_pyramid():
    model = VGG16Flow()
    x = jnp.zeros((1, H, W, 6))
    _, flows = _init_apply(model, x)
    assert len(flows) == 5 and model.flow_scales == (10.0, 5.0, 2.5, 1.25, 0.625)
    for k, f in enumerate(flows):
        assert f.shape == (1, H >> (k + 1), W >> (k + 1), 2)


def test_inception_pyramid():
    model = InceptionV3Flow()
    x = jnp.zeros((1, H, W, 6))
    _, flows = _init_apply(model, x)
    assert len(flows) == 6
    assert model.flow_scales == (10.0, 5.0, 2.5, 2.5, 1.25, 0.625)
    # pr4 and pr3 share a resolution (stride-1 transition)
    assert flows[2].shape == flows[3].shape
    assert flows[0].shape == (1, H // 2, W // 2, 2)
    # the Inception base has 5 stride-2 stages: coarsest tap is /32
    assert flows[5].shape == (1, H // 32, W // 32, 2)


def test_inception_tap_channels():
    """Architecture checksum: tap widths of the standard v3 base."""
    from deepof_tpu.models.inception_v3_flow import InceptionV3Base

    base = InceptionV3Base()
    x = jnp.zeros((1, H, W, 6))
    variables = base.init(jax.random.PRNGKey(0), x)
    taps = base.apply(variables, x)
    want = {"Conv2d_1a_3x3": 32, "MaxPool_3a_3x3": 64, "MaxPool_5a_3x3": 192,
            "Mixed_5d": 288, "Mixed_6e": 768, "Mixed_7c": 2048}
    for k, c in want.items():
        assert taps[k].shape[-1] == c, (k, taps[k].shape)


def test_flownet_c():
    model = FlowNetC(max_disp=4, corr_stride=2)  # small disp for test speed
    x = jnp.zeros((1, H, W, 6))
    variables, flows = _init_apply(model, x)
    assert len(flows) == 6
    assert flows[0].shape == (1, H // 2, W // 2, 2)
    # siamese towers share weights: exactly ONE conv1/conv2/conv3 param set
    names = [k for k in variables["params"] if k.startswith("conv")]
    assert sorted(names) == ["conv1", "conv2", "conv3", "conv3_1", "conv4_1",
                             "conv4_2", "conv5_1", "conv5_2", "conv6_1",
                             "conv6_2", "conv_redir"]


def test_st_single():
    model = STSingle()
    x = jnp.zeros((2, H, W, 6))
    _, (flows, logits) = _init_apply(model, x, train=False)
    assert len(flows) == 5 and logits.shape == (2, 101)


def test_st_baseline():
    model = STBaseline()
    x = jnp.zeros((2, H, W, 6))
    _, (flows, logits) = _init_apply(model, x, train=False)
    assert len(flows) == 6 and logits.shape == (2, 101)


def test_ucf_spatial():
    model = UCF101Spatial()
    x = jnp.zeros((2, H, W, 3))
    _, logits = _init_apply(model, x, train=False)
    assert logits.shape == (2, 101)


def test_dropout_only_in_train_mode():
    model = UCF101Spatial()
    x = jnp.ones((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    a = model.apply(variables, x, train=False)
    b = model.apply(variables, x, train=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = model.apply(variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(1)})
    d = model.apply(variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)})
    # logits are tiny (truncated-normal 0.01 trunk; reference relies on
    # pretrained VGG weights) — compare exactly, not with allclose atol
    assert np.any(np.asarray(c) != np.asarray(d))


def _conv(k, cin, cout):
    """slim conv2d / conv2d_transpose param count: k*k*cin*cout + bias."""
    return k * k * cin * cout + cout


def test_flownet_s_param_parity():
    """Architecture checksum against the reference, layer by layer — the
    param-count convention of `flyingChairsTrain.py:106-118`. The expected
    total is computed analytically from the layer table transcribed from
    `flyingChairsWrapFlow.py:31-40` (encoder) and `:62-118` (decoder:
    upconv_k and pr_k consume the concat(skip, upconv, up_pr) feature,
    concat widths 1026/770/386/194/98)."""
    encoder = [(7, 6, 64), (5, 64, 128), (5, 128, 256), (3, 256, 256),
               (3, 256, 512), (3, 512, 512), (3, 512, 512), (3, 512, 512),
               (3, 512, 1024), (3, 1024, 1024)]
    want = sum(_conv(k, i, o) for k, i, o in encoder)
    feat_in, skips = 1024, [512, 512, 256, 128, 64]
    upconvs = [512, 256, 128, 64, 32]
    for skip, up in zip(skips, upconvs):
        want += _conv(3, feat_in, 2)       # pr_k
        want += _conv(4, feat_in, up)      # upconv_k (4x4, stride 2)
        want += _conv(4, 2, 2)             # up_pr_k
        feat_in = skip + up + 2            # concat(skip, upconv, up_pr)
    want += _conv(3, feat_in, 2)           # pr1 on concat1 (98 ch)

    model = FlowNetS()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 6)))
    assert count_params(variables["params"]) == want


def test_vgg16_flow_param_parity():
    """Same checksum for the VGG16 flow net (`flyingChairsWrapFlow.py:
    653-739`): 13-conv trunk, 5 heads, decoder widths 256/128/64/32,
    concat widths 770/386/194/98."""
    want = 0
    cin = 6
    for cout, n in [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]:
        for _ in range(n):
            want += _conv(3, cin, cout)
            cin = cout
    feat_in, skips = 512, [512, 256, 128, 64]
    upconvs = [256, 128, 64, 32]
    for skip, up in zip(skips, upconvs):
        want += _conv(3, feat_in, 2) + _conv(4, feat_in, up) + _conv(4, 2, 2)
        feat_in = skip + up + 2
    want += _conv(3, feat_in, 2)

    model = VGG16Flow()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 6)))
    assert count_params(variables["params"]) == want


def test_inception_v3_flow_param_parity():
    """Architecture checksum for the flagship model, derived analytically
    from a per-block layer table transcribed from the reference base
    (`flyingChairsWrapFlow.py:145-467`: stem, Mixed_5b-5d pool-proj
    32/64/64, Mixed_6a, Mixed_6b-6e factorized-7x7 mids 128/160/160/192,
    Mixed_7a, Mixed_7b-7c) and head (`:471-595`: taps 2048/768/288/192/
    64/32, upconvs 512/256/128/64/32, the stride-1 2x2 deconv between the
    same-size Mixed_5d and MaxPool_5a taps, `:551-556`) — the same
    convention as the FlowNet-S/VGG16 parity tests. The 44.55M anchor is
    the reference's "%4.2fM" printout figure."""
    def c(kh, kw, cin, cout):  # conv kernel + bias
        return kh * kw * cin * cout + cout

    want = 0
    # stem: Conv2d_1a..Conv2d_4a (pools are param-free)
    want += c(3, 3, 6, 32) + c(3, 3, 32, 32) + c(3, 3, 32, 64)
    want += c(1, 1, 64, 80) + c(3, 3, 80, 192)
    # Mixed_5b/5c/5d: InceptionA(in, pool_proj), out 256/288/288
    for cin, pool in [(192, 32), (256, 64), (288, 64)]:
        want += c(1, 1, cin, 64)                                    # b0
        want += c(1, 1, cin, 48) + c(5, 5, 48, 64)                  # b1
        want += c(1, 1, cin, 64) + c(3, 3, 64, 96) + c(3, 3, 96, 96)  # b2
        want += c(1, 1, cin, pool)                                  # b3
    # Mixed_6a: ReductionA(288) -> 768
    want += c(3, 3, 288, 384)
    want += c(1, 1, 288, 64) + c(3, 3, 64, 96) + c(3, 3, 96, 96)
    # Mixed_6b..6e: InceptionB(768, mid), out 768
    for m in (128, 160, 160, 192):
        want += c(1, 1, 768, 192)                                   # b0
        want += c(1, 1, 768, m) + c(1, 7, m, m) + c(7, 1, m, 192)   # b1
        want += (c(1, 1, 768, m) + c(7, 1, m, m) + c(1, 7, m, m)
                 + c(7, 1, m, m) + c(1, 7, m, 192))                 # b2
        want += c(1, 1, 768, 192)                                   # b3
    # Mixed_7a: ReductionB(768) -> 1280
    want += c(1, 1, 768, 192) + c(3, 3, 192, 320)
    want += (c(1, 1, 768, 192) + c(1, 7, 192, 192) + c(7, 1, 192, 192)
             + c(3, 3, 192, 192))
    # Mixed_7b/7c: InceptionC(1280/2048) -> 2048
    for cin in (1280, 2048):
        want += c(1, 1, cin, 320)                                   # b0
        want += c(1, 1, cin, 384) + c(1, 3, 384, 384) + c(3, 1, 384, 384)
        want += (c(1, 1, cin, 448) + c(3, 3, 448, 384)
                 + c(1, 3, 384, 384) + c(3, 1, 384, 384))           # b2
        want += c(1, 1, cin, 192)                                   # b3
    # decoder: pr_k 3x3 -> 2, upconv/up_pr deconvs with kernel 2*scale
    feat = 2048
    skips = [768, 288, 192, 64, 32]
    ups = [512, 256, 128, 64, 32]
    scales = [2, 2, 1, 2, 2]
    for skip, up, s in zip(skips, ups, scales):
        k = 2 * s
        want += c(3, 3, feat, 2) + c(k, k, feat, up) + c(k, k, 2, 2)
        feat = skip + up + 2
    want += c(3, 3, feat, 2)  # pr1

    model = InceptionV3Flow()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 6)))
    assert count_params(variables["params"]) == want == 44_553_722


def test_bilinear_init_upsamples():
    """A bilinear-initialized 4x4/s2 ConvTranspose must upsample a constant
    image to (nearly) the same constant."""
    from flax import linen as nn

    layer = nn.ConvTranspose(3, (4, 4), strides=(2, 2), padding="SAME",
                             kernel_init=bilinear_kernel_init)
    x = jnp.ones((1, 8, 8, 3)) * 5.0
    variables = layer.init(jax.random.PRNGKey(0), x)
    y = layer.apply(variables, x)
    assert y.shape == (1, 16, 16, 3)
    inner = np.asarray(y)[0, 2:-2, 2:-2]
    np.testing.assert_allclose(inner, 5.0, rtol=1e-5)


def test_registry():
    m = build_model("flownet_s", flow_channels=4)
    assert isinstance(m, FlowNetS) and m.flow_channels == 4
    with pytest.raises(KeyError):
        build_model("nope")


def test_correlation_matches_oracle(rng):
    from deepof_tpu.ops.corr import correlation, correlation_oracle

    f1 = rng.randn(2, 6, 7, 4).astype(np.float32)
    f2 = rng.randn(2, 6, 7, 4).astype(np.float32)
    got = np.asarray(correlation(jnp.asarray(f1), jnp.asarray(f2), max_disp=2, stride=1))
    want = correlation_oracle(f1, f2, max_disp=2, stride=1)
    assert got.shape == (2, 6, 7, 25)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_correlation_stride(rng):
    from deepof_tpu.ops.corr import correlation, correlation_oracle

    f1 = rng.randn(1, 8, 8, 3).astype(np.float32)
    f2 = rng.randn(1, 8, 8, 3).astype(np.float32)
    got = np.asarray(correlation(jnp.asarray(f1), jnp.asarray(f2), max_disp=4, stride=2))
    want = correlation_oracle(f1, f2, max_disp=4, stride=2)
    assert got.shape[-1] == 25  # K = max_disp//stride = 2 -> (2K+1)^2
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_load_vgg16_npz(tmp_path, rng):
    """Pretrained VGG conv import: 13 conv layers in order, first conv
    tiled x2 along in-channels for the 6-channel pair input
    (`flyingChairsTrain.py:60-76`)."""
    from deepof_tpu.models import load_vgg16_npz

    widths = {1: (64, 2), 2: (128, 2), 3: (256, 3), 4: (512, 3), 5: (512, 3)}
    data = {}
    cin = 3
    for b, (cout, n) in widths.items():
        c = cin
        for i in range(1, n + 1):
            data[f"conv{b}_{i}_W"] = rng.randn(3, 3, c, cout).astype(np.float32)
            data[f"conv{b}_{i}_b"] = rng.randn(cout).astype(np.float32)
            c = cout
        cin = cout
    npz = str(tmp_path / "vgg16_weights.npz")
    np.savez(npz, **data)

    model = build_model("vgg16")
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, H, W, 6)))["params"]
    loaded = load_vgg16_npz(params, npz)

    first = np.asarray(loaded["encoder"]["conv1_1"]["Conv_0"]["kernel"])
    np.testing.assert_array_equal(
        first, np.concatenate([data["conv1_1_W"]] * 2, axis=2))
    np.testing.assert_array_equal(
        np.asarray(loaded["encoder"]["conv5_3"]["Conv_0"]["kernel"]),
        data["conv5_3_W"])
    np.testing.assert_array_equal(
        np.asarray(loaded["encoder"]["conv3_2"]["Conv_0"]["bias"]),
        data["conv3_2_b"])
    # decoder untouched
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, loaded["decoder"], params["decoder"])


def test_load_vgg16_npz_relu_trunk(tmp_path, rng):
    """_VGGReLUTrunk models (ucf101_spatial, st_baseline spatial stream)
    name nn.Conv directly (no Conv_0 nesting); 3-channel input -> no
    first-layer duplication."""
    from deepof_tpu.models import load_vgg16_npz

    widths = {1: (64, 2), 2: (128, 2), 3: (256, 3), 4: (512, 3), 5: (512, 3)}
    data = {}
    cin = 3
    for b, (cout, n) in widths.items():
        c = cin
        for i in range(1, n + 1):
            data[f"conv{b}_{i}_W"] = rng.randn(3, 3, c, cout).astype(np.float32)
            data[f"conv{b}_{i}_b"] = rng.randn(cout).astype(np.float32)
            c = cout
        cin = cout
    npz = str(tmp_path / "vgg16_weights.npz")
    np.savez(npz, **data)

    model = build_model("ucf101_spatial")
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, H, W, 3)))["params"]
    loaded = load_vgg16_npz(params, npz)
    trunk = loaded["encoder"]["conv1_1"]
    tgt = trunk.get("Conv_0", trunk)
    np.testing.assert_array_equal(np.asarray(tgt["kernel"]), data["conv1_1_W"])

    model = build_model("st_baseline")
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, H, W, 6)))["params"]
    loaded = load_vgg16_npz(params, npz, trunk_path=("spatial",))
    trunk = loaded["spatial"]["conv5_3"]
    tgt = trunk.get("Conv_0", trunk)
    np.testing.assert_array_equal(np.asarray(tgt["kernel"]), data["conv5_3_W"])


def test_flownet_cs_stacked_refinement():
    """FlowNet-CS (FlowNet2-style stack): base + warp-fed refinement;
    gradients reach the base network through the warp's flow input."""
    model = build_model("flownet_cs", max_disp=4)  # small corr for test speed
    x = jnp.zeros((1, H, W, 6))
    variables, flows = _init_apply(model, x)
    assert len(flows) == 6
    assert flows[0].shape == (1, H // 2, W // 2, 2)
    assert {"base", "refine"} <= set(variables["params"].keys())

    rng = np.random.RandomState(0)
    xr = jnp.asarray(rng.rand(1, H, W, 6), jnp.float32)

    def loss(params):
        f = model.apply({"params": params}, xr)
        return jnp.sum(jnp.square(f[0]))

    grads = jax.grad(loss)(variables["params"])
    gbase = max(float(jnp.abs(g).max())
                for g in jax.tree_util.tree_leaves(grads["base"]))
    assert gbase > 0.0, "no gradient reached the base stage through the warp"

    with pytest.raises(ValueError, match="2-frame"):
        build_model("flownet_cs", flow_channels=4, max_disp=4).init(
            jax.random.PRNGKey(0), jnp.zeros((1, H, W, 12)))
