"""Latency-hiding loop mechanics: AsyncFetcher overlap, StepTimer phases,
Prefetcher device staging. Pure host-side — no model compiles — so these
run in the fast tier and pin the ISSUE r06 acceptance on CPU: under an
injected 50 ms fetch delay the pipelined dispatch/fetch loop sustains
>= 2 calls in flight and beats the serial loop's wall-clock.
"""

import threading
import time

import numpy as np
import pytest

from deepof_tpu.train.metrics_log import AsyncFetcher, StepTimer, SyncFetcher

FETCH_DELAY = 0.05  # the ISSUE-specified injected 50 ms value-fetch RTT
N_STEPS = 10


def _delayed_fetch(tree):
    time.sleep(FETCH_DELAY)
    return tree


def _run_loop(fetcher, dispatch_s=0.04):
    """A train loop skeleton: dispatch-side host work then the metrics
    fetch submit; every step is host-visible (log_every=1). Dispatch and
    fetch delays are comparable (40 vs 50 ms — the measured tunnel RTT
    regime), so overlap should cut wall-clock to ~max(sum_dispatch,
    sum_fetch) while the serial loop pays their sum."""
    done = []
    t0 = time.perf_counter()
    for i in range(N_STEPS):
        time.sleep(dispatch_s)  # stand-in for the async dispatch call
        fetcher.submit(i, {"total": np.float32(i)},
                       lambda tag, host: done.append(tag))
    fetcher.drain()
    wall = time.perf_counter() - t0
    fetcher.close()
    return wall, done


def test_pipelined_loop_beats_serial_under_fetch_delay():
    """The acceptance pin: >= 2 calls in flight, and wall-clock clearly
    under the serial dispatch+fetch sum (which is ~N*(dispatch+fetch))."""
    serial_wall, serial_done = _run_loop(SyncFetcher(fetch_fn=_delayed_fetch))
    pipe = AsyncFetcher(depth=2, fetch_fn=_delayed_fetch)
    pipe_wall, pipe_done = _run_loop(pipe)

    assert serial_done == list(range(N_STEPS))
    assert pipe_done == list(range(N_STEPS))  # FIFO: records stay ordered
    assert pipe.stats()["max_in_flight"] >= 2
    assert pipe.stats()["fetches"] == N_STEPS
    # serial pays ~N*55ms; pipelined hides the fetch behind dispatch and
    # is bounded by ~N*50ms fetch drain alone. Demand a real margin, not
    # a scheduler wiggle.
    assert pipe_wall < serial_wall * 0.85, (pipe_wall, serial_wall)


def test_async_fetcher_bounded_depth_blocks_dispatch():
    """The honesty mechanism: with depth=1, submit() cannot run ahead —
    the dispatch clock stays within one unfetched call of completion."""
    f = AsyncFetcher(depth=1, fetch_fn=_delayed_fetch)
    t0 = time.perf_counter()
    for i in range(4):
        f.submit(i, i, lambda tag, host: None)
    submit_wall = time.perf_counter() - t0
    f.drain()
    f.close()
    # 4 submits against depth 1: at least 2 fetch delays serialized into
    # the submit path (would be ~0 if the bound leaked)
    assert submit_wall > 2 * FETCH_DELAY
    # the bound is exact: never more than `depth` submitted-but-unfetched
    assert f.stats()["max_in_flight"] == 1


def test_async_fetcher_close_never_blocks_on_wedged_consumer():
    """Teardown robustness: a consumer stuck in a hung device_get (dead
    tunnel) must not block close() — fit()'s finally has to reach
    prefetch.close()/ckpt.finalize(). The stop sentinel goes onto an
    unbounded queue, and the daemon thread is abandoned after the join
    timeout."""
    wedged = threading.Event()

    def hang_fetch(tree):
        wedged.set()
        time.sleep(60)  # daemon thread: abandoned at interpreter exit
        return tree

    f = AsyncFetcher(depth=1, fetch_fn=hang_fetch)
    f.submit(0, 0, lambda tag, host: None)
    assert wedged.wait(5.0)  # consumer is now stuck inside the fetch
    t0 = time.perf_counter()
    f.close()  # must return via the join timeout, not hang on a put
    assert time.perf_counter() - t0 < 10.0


def test_async_fetcher_callback_error_surfaces():
    f = AsyncFetcher(depth=2, fetch_fn=lambda t: t)

    def boom(tag, host):
        raise ValueError("callback exploded")

    f.submit(0, 0, boom)
    with pytest.raises(ValueError, match="callback exploded"):
        f.drain()  # join guarantees the callback ran before the re-raise
    f.close()


def test_sync_fetcher_is_inline():
    """Depth-0 fallback runs fetch+callback on the caller's thread."""
    caller = threading.get_ident()
    seen = {}

    def cb(tag, host):
        seen["thread"] = threading.get_ident()
        seen["host"] = host

    f = SyncFetcher(fetch_fn=lambda t: t + 1)
    f.submit(0, 41, cb)
    assert seen == {"thread": caller, "host": 42}
    assert f.stats()["fetches"] == 1


def test_step_timer_phases_accumulate_and_reset():
    t = StepTimer(items_per_step=4)
    t.phase("dispatch", 0.1)
    t.phase("dispatch", 0.2)
    t.phase("fetch", 0.05)
    p = t.phases()
    assert abs(p["phase_dispatch_s"] - 0.3) < 1e-9
    assert abs(p["phase_fetch_s"] - 0.05) < 1e-9
    t.reset()
    assert t.phases() == {}


def test_async_fetcher_records_fetch_phase():
    timer = StepTimer(items_per_step=1)
    f = AsyncFetcher(depth=2, fetch_fn=_delayed_fetch, timer=timer)
    for i in range(3):
        f.submit(i, i, lambda tag, host: None)
    f.drain()
    f.close()
    assert timer.phases()["phase_fetch_s"] >= 3 * FETCH_DELAY * 0.9


def test_prefetcher_stages_on_device_and_reports_put_phase():
    """stage=True: get() returns committed jax arrays (transfer already
    complete) and the put phase lands in the timer from the producer
    thread."""
    import jax

    from deepof_tpu.data.prefetch import Prefetcher

    timer = StepTimer(items_per_step=1)
    produced = {"n": 0}

    def produce():
        produced["n"] += 1
        return {"x": np.ones((4, 4), np.float32) * produced["n"]}

    pf = Prefetcher(produce, depth=2, stage=True, phase_cb=timer.phase)
    try:
        b = pf.get()
        assert isinstance(b["x"], jax.Array)
        assert b["x"].is_fully_addressable
        assert "phase_put_s" in timer.phases()
    finally:
        pf.close()


def test_prefetcher_default_stays_host_side():
    """Without stage/sharding the old contract holds: host numpy out."""
    from deepof_tpu.data.prefetch import Prefetcher

    pf = Prefetcher(lambda: {"x": np.zeros(3)}, depth=1)
    try:
        assert isinstance(pf.get()["x"], np.ndarray)
    finally:
        pf.close()
