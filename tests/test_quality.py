"""Label-free flow-quality observability (ISSUE 13, DESIGN.md "Quality
observability"): the census op's first direct unit tests, numpy-vs-jnp
scorer parity, deterministic sampling, the drop-not-block contract under
a wedged scorer, the drift verdict (fires on an injected shift, quiet on
the control), exact fleet merging of the quality histograms, `tail` exit
code 7, the per-scale training-loss telemetry, the eval-EPE trend block,
and the bench_trend / serve_bench --quality report schemas.

Fast tier throughout except the 2-replica chaos drill (chaos marker,
jax-free fake-executor replicas — the test_fleet cost profile).
"""

import dataclasses
import json
import os
import threading
import time

import numpy as np
import pytest

from deepof_tpu.core.config import get_config
from deepof_tpu.obs.export import (QUALITY_BUCKETS, ValueHistogram,
                                   merge_hists, parse_prometheus,
                                   percentile_ms, render_prometheus)
from deepof_tpu.obs.quality import (QualitySampler, QualityScorer,
                                    census_descriptors_np,
                                    census_distance_np, score_pair_np,
                                    warp_bilinear_np)
from deepof_tpu.obs.registry import merge_stats_blocks
from deepof_tpu.serve.engine import InferenceEngine, make_fake_forward

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _quality_cfg(rate=1.0, max_batch=4, timeout_ms=2.0, ref_samples=4,
                 queue_depth=128, budget=0.1, image_size=(32, 64), **obs_kw):
    cfg = get_config("flyingchairs")
    return cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=image_size, gt_size=image_size),
        serve=dataclasses.replace(cfg.serve, max_batch=max_batch,
                                  batch_timeout_ms=timeout_ms,
                                  host="127.0.0.1", port=0),
        obs=dataclasses.replace(cfg.obs, quality_sample_rate=rate,
                                quality_ref_samples=ref_samples,
                                quality_queue_depth=queue_depth,
                                quality_budget=budget, **obs_kw),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6)))


def _pairs(rng, n, hw=(30, 60)):
    return [(rng.randint(0, 255, (*hw, 3), dtype=np.uint8),
             rng.randint(0, 255, (*hw, 3), dtype=np.uint8))
            for _ in range(n)]


# ------------------------------------------------- census op (ops/census)


def test_census_transform_shape_and_descriptor_semantics(rng):
    """First direct unit tests for ops/census.py (no consumer had any):
    descriptor shape, bounded soft-sign values, and zero self-distance."""
    from deepof_tpu.ops.census import census_distance, census_transform

    img = rng.rand(2, 12, 16, 3).astype(np.float32)
    desc = np.asarray(census_transform(img, window=5))
    assert desc.shape == (2, 12, 16, 25)
    # soft-sign components live strictly inside (-1, 1)
    assert np.all(desc > -1.0) and np.all(desc < 1.0)
    # self-distance is exactly zero; distance is symmetric and positive
    # for distinct images
    d_self = np.asarray(census_distance(desc, desc))
    assert d_self.shape == (2, 12, 16, 1)
    assert np.all(d_self == 0.0)
    other = np.asarray(census_transform(
        rng.rand(2, 12, 16, 3).astype(np.float32), window=5))
    d_ab = np.asarray(census_distance(desc, other))
    d_ba = np.asarray(census_distance(other, desc))
    assert np.allclose(d_ab, d_ba)
    assert float(d_ab.mean()) > 0.1
    # saturating per-neighbor penalty: bounded by the window size
    assert float(d_ab.max()) < 25.0


def test_census_illumination_robustness_vs_charbonnier(rng):
    """The property census exists for: a global brightness shift moves
    the raw photometric distance a lot and the census distance barely —
    the pair distinguishes 'flows degraded' from 'the scene got darker'.
    """
    from deepof_tpu.ops.census import census_distance, census_transform

    img = rng.rand(1, 16, 20, 3).astype(np.float32) * 0.5 + 0.2
    brighter = img + 0.2  # global additive illumination change
    d_census = float(np.asarray(census_distance(
        census_transform(img), census_transform(brighter)))[
            :, 4:-4, 4:-4].mean())
    d_raw = float(np.mean(np.abs(img - brighter))) * 255.0
    # raw photometric sees a 51-gray-level shift; census sees almost
    # nothing (edge-replicated border components excluded)
    assert d_raw > 50.0
    assert d_census < 2.0


def test_census_numpy_reference_matches_ops(rng):
    """The scorer's numpy census (obs/quality.py) is the same transform
    as ops/census.py — pinned so the jax-free replica path and the
    jitted path can never drift apart."""
    from deepof_tpu.ops.census import census_distance, census_transform
    from deepof_tpu.ops.smoothness import to_grayscale

    img = rng.rand(1, 10, 14, 3).astype(np.float32)
    ref = np.asarray(census_transform(img, window=5))
    gray = np.asarray(to_grayscale(img * 255.0))[0]
    got = census_descriptors_np(gray, window=5)
    np.testing.assert_allclose(got, ref[0], rtol=1e-5, atol=1e-6)
    other = rng.rand(*got.shape).astype(np.float32)
    np.testing.assert_allclose(
        census_distance_np(got, other),
        np.asarray(census_distance(got[None], other[None]))[0],
        rtol=1e-5, atol=1e-6)


# ----------------------------------------------------- scorer math parity


def test_score_fn_numpy_jnp_parity(rng):
    """The jitted scorer (real-model engines) and the numpy reference
    (jax-free fake-executor replicas) agree to float precision at every
    grid relationship — equal, and downsampled flow grids."""
    import jax

    from deepof_tpu.obs.quality import make_score_fn

    jfn = jax.jit(make_score_fn())
    for shape, fshape in (((16, 16), (16, 16)), ((32, 48), (8, 12)),
                          ((30, 60), (8, 16))):
        x = rng.rand(*shape, 6).astype(np.float32) - 0.4
        flow = (rng.rand(*fshape, 2).astype(np.float32) - 0.5) * 3.0
        jv = np.asarray(jfn(x[None], flow[None]))
        nv = np.array(score_pair_np(x, flow))
        np.testing.assert_allclose(jv, nv, rtol=1e-4, atol=1e-5)


def test_numpy_warp_matches_ops_warp(rng):
    """warp_bilinear_np == ops/warp.backward_warp for in-bounds flows
    (the proxy's operating regime; the left/top saturation corner where
    the XLA path zeroes the fractional weight is excluded by keeping
    displacements inside the frame)."""
    from deepof_tpu.ops.warp import backward_warp

    img = rng.rand(1, 12, 14, 3).astype(np.float32)
    flow = (rng.rand(1, 12, 14, 2).astype(np.float32) - 0.5) * 2.0
    ref = np.asarray(backward_warp(img, flow, impl="xla"))[0]
    got = warp_bilinear_np(img[0], flow[0])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_perfect_flow_scores_better_than_wrong_flow(rng):
    """The proxy is a quality signal, not noise: for a pure-translation
    pair, the true flow scores a (much) lower photo/census error than a
    wrong flow."""
    base = rng.randint(0, 255, (40, 52, 3)).astype(np.float32)
    shift = 3
    f1 = base[:, shift:, :] / 255.0   # f1[y, x] = base[y, x + 3]
    f2 = base[:, :-shift, :] / 255.0  # f2[y, x] = base[y, x]
    x = np.concatenate([f1, f2], axis=-1).astype(np.float32) - 0.4
    h, w = f1.shape[:2]
    true_flow = np.full((h, w, 2), 0.0, np.float32)
    true_flow[..., 0] = shift  # recon[y, x] = f2[y, x + 3] == f1[y, x]
    wrong_flow = -true_flow
    p_true, _, c_true = score_pair_np(x, true_flow)
    p_wrong, _, c_wrong = score_pair_np(x, wrong_flow)
    assert p_true < 0.5 * p_wrong
    assert c_true < 0.5 * c_wrong


# ------------------------------------------------------------- sampling


def test_sampler_deterministic_and_rate_faithful():
    s1 = QualitySampler(0.3, seed=7)
    s2 = QualitySampler(0.3, seed=7)
    picks1 = [s1.sample(i) for i in range(2000)]
    picks2 = [s2.sample(i) for i in range(2000)]
    assert picks1 == picks2  # pure in (seed, index)
    frac = sum(picks1) / len(picks1)
    assert 0.25 < frac < 0.35
    # a different seed samples a different set at the same rate
    assert [QualitySampler(0.3, seed=8).sample(i)
            for i in range(2000)] != picks1
    assert not any(QualitySampler(0.0, seed=7).sample(i) for i in range(50))
    assert all(QualitySampler(1.0, seed=7).sample(i) for i in range(50))


def test_engine_sampled_set_independent_of_batching(rng):
    """The sampled SET is a pure function of submission order: engines
    differing in max_batch (and so in batching/flush interleaving)
    sample exactly the same count from the same sequential workload."""
    pairs = _pairs(rng, 24)
    counts = []
    for max_batch in (1, 4):
        cfg = _quality_cfg(rate=0.5, max_batch=max_batch)
        with InferenceEngine(cfg,
                             forward_fn=make_fake_forward(0.5)) as eng:
            for prev, nxt in pairs:
                eng.submit(prev, nxt).result(30)
            assert eng._quality.drain(30)
            counts.append(eng.stats()["serve_quality_sampled"])
    assert counts[0] == counts[1]
    assert 0 < counts[0] < 24  # genuinely a sample, not all-or-nothing


# -------------------------------------------- off-path + parity contracts


def test_rate_zero_is_schema_and_response_invariant(rng):
    """obs.quality_sample_rate=0 (the default): no scorer exists, no
    serve_quality_* key appears anywhere in stats, and the flows are
    bitwise identical to a sampling engine's — scoring observes
    responses, never participates in them."""
    pairs = _pairs(rng, 8)

    def flows_at(rate):
        with InferenceEngine(_quality_cfg(rate=rate),
                             forward_fn=make_fake_forward(0.5)) as eng:
            out = [eng.submit(p, n).result(30)["flow"] for p, n in pairs]
            stats = eng.stats()
            quality = eng._quality
        return out, stats, quality

    off_flows, off_stats, off_quality = flows_at(0.0)
    on_flows, on_stats, on_quality = flows_at(1.0)
    assert off_quality is None
    assert on_quality is not None
    assert not any(k.startswith("serve_quality") for k in off_stats)
    assert any(k.startswith("serve_quality") for k in on_stats)
    for a, b in zip(off_flows, on_flows):
        assert np.array_equal(a, b)


def test_wedged_scorer_drops_never_blocks(rng):
    """The hot-path contract: a scorer wedged mid-score (queue_depth 1)
    costs SAMPLES (dropped-and-counted), never latency — every response
    resolves promptly while the scorer thread is stuck."""
    wedge = threading.Event()
    release = threading.Event()

    cfg = _quality_cfg(rate=1.0, queue_depth=1)
    with InferenceEngine(cfg, forward_fn=make_fake_forward(0.5)) as eng:

        def stuck_score(bucket, x, flow):
            wedge.set()
            release.wait(30)  # wedged until the test releases it
            return (1.0, 0.0, 0.0)

        eng._quality._score_fn = stuck_score
        pairs = _pairs(rng, 12)
        t0 = time.monotonic()
        futs = [eng.submit(p, n) for p, n in pairs]
        for f in futs:
            f.result(30)
        wall = time.monotonic() - t0
        assert wedge.wait(10)
        stats = eng.stats()
        release.set()  # let close() drain
        time.sleep(0.2)  # scorer empties its 1-slot queue before close
    assert wall < 10.0  # responses never waited on the wedged scorer
    assert stats["serve_quality_dropped"] >= 1
    assert (stats["serve_quality_sampled"]
            + stats["serve_quality_dropped"]) == 12


# ---------------------------------------------------------- drift verdict


def _controlled_scorer(**kw):
    """A QualityScorer whose photo value is the flow's [0,0,0] entry —
    the drift machinery driven with exact, chosen values."""
    return QualityScorer(
        lambda bucket, x, flow: (float(flow[0, 0, 0, 0]), 0.1, 0.2),
        sample_rate=1.0, ref_samples=4, drift_factor=2.0, budget=0.25,
        **kw)


def _feed(scorer, photo_values):
    x = np.zeros((2, 2, 6), np.float32)
    for v in photo_values:
        flow = np.full((1, 1, 2), v, np.float32)
        assert scorer.submit(x, flow, (2, 2), "f32", "cold")
    assert scorer.drain(30)


def test_drift_verdict_fires_on_shift_quiet_on_control():
    # control: stable distribution around the reference -> no breaches
    control = _controlled_scorer()
    try:
        _feed(control, [1.0, 1.1, 0.9, 1.0] + [1.0, 1.2, 0.8] * 6)
        v = control.stats()["serve_quality"]
        assert v["ref_p50"] == pytest.approx(1.0, abs=0.1)
        assert v["breaches"] == 0
        assert v["burn"] == 0.0
        assert v["exhausted"] is False
    finally:
        control.close()
    # shifted: post-reference photo error jumps past ref_p50 * factor
    shifted = _controlled_scorer()
    try:
        _feed(shifted, [1.0, 1.1, 0.9, 1.0] + [5.0] * 12)
        v = shifted.stats()["serve_quality"]
        assert v["breaches"] == 12
        assert v["bad_fraction"] == 1.0
        assert v["burn"] == pytest.approx(4.0)
        assert v["exhausted"] is True
        assert v["drift_ratio"] > 2.0
    finally:
        shifted.close()


def test_drift_reference_freezes_before_shift():
    """The reference forms from the FIRST ref_samples scored requests
    and never moves: a later shift cannot drag the baseline with it."""
    s = _controlled_scorer()
    try:
        _feed(s, [1.0] * 4)
        assert s.stats()["serve_quality"]["ref_p50"] == pytest.approx(1.0)
        _feed(s, [5.0] * 8)
        v = s.stats()["serve_quality"]
        assert v["ref_p50"] == pytest.approx(1.0)  # frozen
        assert v["current_p50"] == pytest.approx(5.0)
    finally:
        s.close()


# ----------------------------------------------------- merge / prometheus


def test_quality_stats_merge_exactly_by_registry_kind(rng):
    """Two engines' quality blocks merge by the registry's declared
    kinds: counters sum, per-key maps sum key-wise, the fixed-bucket
    histograms merge EXACTLY, derived verdict blocks drop."""
    blocks, hists = [], []
    for _ in range(2):
        with InferenceEngine(_quality_cfg(rate=1.0),
                             forward_fn=make_fake_forward(0.5)) as eng:
            for prev, nxt in _pairs(rng, 6):
                eng.submit(prev, nxt).result(30)
            assert eng._quality.drain(30)
            s = eng.stats()
        blocks.append({k: v for k, v in s.items()
                       if k.startswith("serve_")})
        hists.append(s["serve_quality_photo_hist"])
    merged = merge_stats_blocks(blocks)
    assert merged["serve_quality_scored"] == 12
    assert merged["serve_quality_scored_by_key"]["f32/cold"] == 12
    expect = merge_hists(hists)
    assert merged["serve_quality_photo_hist"] == expect
    for i in range(len(QUALITY_BUCKETS) + 1):
        assert expect["counts"][i] == sum(h["counts"][i] for h in hists)
    assert "serve_quality" not in merged  # derived: re-derive, never sum
    assert "serve_quality_photo_p50" not in merged


def test_quality_histogram_prometheus_render_is_unitless():
    """Quality histograms render without the latency "_ms" unit suffix
    (their bounds are raw proxy units) and round-trip the parser."""
    h = ValueHistogram(QUALITY_BUCKETS)
    for v in (0.01, 1.5, 900.0, 1e5):
        h.observe(v)
    text = render_prometheus({"serve_quality_photo_hist": h.snapshot()})
    assert "deepof_serve_quality_photo_ms" not in text
    parsed = parse_prometheus(text)
    assert parsed['deepof_serve_quality_photo_bucket{le="+Inf"}'] == 4
    assert parsed["deepof_serve_quality_photo_count"] == 4
    # the percentile reads off the shared fixed bounds
    assert percentile_ms(h.snapshot(), 0.5) in QUALITY_BUCKETS


# ------------------------------------------------------------ tail rc 7


def test_tail_exits_7_on_quality_drift(tmp_path, capsys):
    from deepof_tpu.cli import main as cli_main

    def run_dir(name, exhausted):
        d = tmp_path / name
        d.mkdir()
        (d / "metrics.jsonl").write_text("")
        (d / "heartbeat.json").write_text(json.dumps({
            "time": time.time(), "pid": os.getpid(), "step": 0,
            "serve_requests": 50, "serve_responses": 50,
            "serve_quality": {"scored": 50, "breaches": 20,
                              "bad_fraction": 0.4, "budget": 0.1,
                              "burn": 4.0, "exhausted": exhausted}}))
        return d

    rc = cli_main(["tail", "--log-dir", str(run_dir("drift", True))])
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["serve"]["quality"]["exhausted"] is True
    assert rc == 7
    assert cli_main(["tail", "--log-dir",
                     str(run_dir("control", False))]) == 0


def test_tail_fleet_exits_7_on_a_child_replicas_drift(tmp_path, capsys):
    """The degraded replica's verdict lives in ITS process dir; `tail
    --fleet` on the fleet root must surface it as rc 7."""
    from deepof_tpu.cli import main as cli_main

    (tmp_path / "metrics.jsonl").write_text("")
    child = tmp_path / "replica-1"
    child.mkdir()
    rec = {"kind": "serve", "step": 0, "time": time.time(),
           "serve_requests": 40, "serve_responses": 40,
           "serve_quality": {"scored": 40, "breaches": 30,
                             "bad_fraction": 0.75, "budget": 0.1,
                             "burn": 7.5, "exhausted": True}}
    (child / "metrics.jsonl").write_text(json.dumps(rec) + "\n")
    assert cli_main(["tail", "--log-dir", str(tmp_path)]) == 0  # no --fleet
    capsys.readouterr()
    rc = cli_main(["tail", "--log-dir", str(tmp_path), "--fleet"])
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["processes"]["replica-1"]["serve"]["quality"][
        "exhausted"] is True
    assert rc == 7


# --------------------------------------- per-scale training-loss records


def test_loss_dict_carries_smooth_alias(rng):
    """losses/photometric.py: every loss dict now names its smoothness
    component; smooth == U_loss + V_loss exactly."""
    from deepof_tpu.core.config import LossConfig
    from deepof_tpu.losses.photometric import loss_interp

    img = rng.rand(1, 16, 20, 3).astype(np.float32)
    flow = (rng.rand(1, 16, 20, 2).astype(np.float32) - 0.5) * 2.0
    ld, _ = loss_interp(flow, img, img, 1.0, LossConfig())
    assert float(ld["smooth"]) == pytest.approx(
        float(ld["U_loss"]) + float(ld["V_loss"]), rel=1e-6)


def test_per_scale_record_fields_shape():
    """train/loop.py per_scale_last + SCALE_RECORD_FIELDS: per-scale
    vectors fold into JSON lists, last inner step wins under
    steps_per_call stacking."""
    from deepof_tpu.train.loop import SCALE_RECORD_FIELDS, per_scale_last

    assert [f for f, _ in SCALE_RECORD_FIELDS] == [
        "loss_total_by_scale", "loss_photo_by_scale",
        "loss_smooth_by_scale"]
    v = np.array([1.0, 0.5, 0.25])
    assert per_scale_last(v) == [1.0, 0.5, 0.25]
    stacked = np.stack([v, v * 2.0])  # [K=2, S=3]: last step wins
    assert per_scale_last(stacked) == [2.0, 1.0, 0.5]
    assert json.dumps(per_scale_last(v))  # JSON-ready


def test_train_step_metrics_carry_scale_smooth(rng):
    """train/step.py stacks the smooth component per scale alongside the
    reference-named keys — the record decomposition's device half."""
    import jax.numpy as jnp

    from deepof_tpu.core.config import LossConfig
    from deepof_tpu.losses.pyramid import pyramid_loss

    img = jnp.asarray(rng.rand(1, 16, 16, 3).astype(np.float32))
    pyramid = [(jnp.zeros((1, 8, 8, 2)), 1.0),
               (jnp.zeros((1, 4, 4, 2)), 2.0)]
    _, losses, _ = pyramid_loss(pyramid, img, img, LossConfig())
    for d in losses:
        assert "smooth" in d and "Charbonnier_reconstruct" in d


def test_analyze_surfaces_scale_fields_and_eval_trend():
    from deepof_tpu.analyze import eval_trend, summarize

    records = [
        {"kind": "train", "step": 100, "time": 1.0, "loss": 2.0,
         "loss_photo_by_scale": [1.5, 0.4], "loss_smooth_by_scale":
         [0.1, 0.02], "loss_total_by_scale": [1.6, 0.42]},
    ] + [{"kind": "eval", "step": s, "aee": a}
         for s, a in ((100, 5.0), (200, 4.0), (300, 3.5), (400, 3.4))]
    out = summarize(records)
    assert out["train"]["loss_photo_by_scale"] == [1.5, 0.4]
    assert out["eval_trend"]["regressing"] is False
    assert out["eval_trend"]["slope_aee_per_kstep"] < 0
    # a sustained climb past best flags as regressing with a + slope
    climbing = [{"kind": "eval", "step": s, "aee": a}
                for s, a in ((100, 3.0), (200, 3.3), (300, 3.8),
                             (400, 4.5))]
    trend = eval_trend(climbing)
    assert trend["regressing"] is True
    assert trend["slope_aee_per_kstep"] > 0
    assert trend["best_aee"] == 3.0
    # too few evals: no trend (never a crash)
    assert eval_trend(climbing[:2]) is None


# ------------------------------------------------------- report schemas


def test_bench_trend_schema_and_regression_flag(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_trend", os.path.join(REPO, "tools", "bench_trend.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    # two synthetic rounds: serve proxy collapses 50% in the newer one
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "serve_bench": {"value": 400.0, "speedup_vs_serial": 4.0},
        "data_bench": {"workers0": {"value": 100.0}}}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "serve_bench": {"value": 200.0, "speedup_vs_serial": 4.1},
        "data_bench": {"workers0": {"value": 101.0}}}))
    report = mod.bench_trend(str(tmp_path), tolerance=0.3)
    for key in mod.REQUIRED_KEYS:
        assert key in report, key
    assert report["rounds"] == [1, 2] and report["latest_round"] == 2
    serve = report["series"]["bench_serve_requests_per_s"]
    assert [p["value"] for p in serve] == [400.0, 200.0]
    assert "bench_serve_requests_per_s" in report["regressions"]
    assert report["regressions"]["bench_serve_requests_per_s"][
        "worse_frac"] == pytest.approx(0.5)
    # the improved proxies did not flag
    assert "bench_data_w0_batches_per_s" not in report["regressions"]
    # the real repo's BENCH files parse without error
    real = mod.bench_trend(REPO)
    assert real["latest_round"] >= 12
    assert real["series"]["bench_serve_requests_per_s"]


def test_serve_bench_quality_schema(tmp_path):
    """serve_bench --quality (real flownet_s, one tier to stay fast):
    pinned top-level + per-tier keys, proxies finite and positive,
    overhead pair present."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(REPO, "tools", "serve_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    res = mod.quality_bench(requests=4, gap_ms=0.0, max_batch=2,
                            timeout_ms=2.0, bucket=(32, 64),
                            native_hw=(30, 60), tiers=("f32",),
                            sample_rate=0.5)
    for key in mod.QUALITY_REQUIRED_KEYS:
        assert key in res, key
    tier = res["tiers"]["f32"]
    for key in mod.QUALITY_TIER_REQUIRED_KEYS:
        assert key in tier, key
    assert tier["scored"] == 4
    for proxy in ("photo", "smooth", "census"):
        assert tier[proxy] is not None and np.isfinite(tier[proxy])
        assert tier[proxy] >= 0
    assert res["quality"]["scored"] == 4
    assert res["rps_quality_off"] and res["rps_quality_on"]


# --------------------------------------------- fleet chaos acceptance


@pytest.mark.chaos
def test_fleet_quality_merge_exact_and_degraded_replica_drift(rng,
                                                              tmp_path):
    """ISSUE 13 chaos acceptance, live 2-replica fleet with sampling on:
    (1) the router's /metrics quality-histogram bucket counts EXACTLY
    equal the sum of the replicas' /healthz counts; (2) an injected
    degraded-weights replica (replica_degrade: every dispatch past the
    arm point returns corrupted flow — latency/SLO stay perfect) trips
    the drift verdict and `tail --fleet` exits 7, while the control
    fleet stays rc 0."""
    cv2 = pytest.importorskip("cv2")
    import base64

    from test_fleet import _fleet_cfg, _get_json, _post, _start_router
    from deepof_tpu.cli import main as cli_main
    from deepof_tpu.serve.fleet import Fleet

    def still_body(hw):
        """prev == next (a textured STILL frame): the fake executor's
        flow (channel difference) is exactly zero, so the healthy proxy
        is near its floor and a degraded replica's corrupted flow (+25
        px on a textured image) shifts it unmistakably — the structured
        workload that makes drift visible on the fake executor."""
        img = rng.randint(1, 255, (*hw, 3), dtype=np.uint8)
        ok, buf = cv2.imencode(".png", img)
        assert ok
        b64 = base64.b64encode(buf.tobytes()).decode()
        return json.dumps({"prev": b64, "next": b64}).encode()

    def quality_fleet_cfg(log_dir, degrade=False):
        cfg = _fleet_cfg(log_dir, max_batch=4, timeout_ms=5.0, exec_ms=2.0,
                         buckets=((32, 64), (64, 64)))
        cfg = cfg.replace(obs=dataclasses.replace(
            cfg.obs, quality_sample_rate=1.0, quality_ref_samples=4,
            quality_budget=0.1))
        if degrade:
            cfg = cfg.replace(resilience=dataclasses.replace(
                cfg.resilience, faults=dataclasses.replace(
                    cfg.resilience.faults, enabled=True,
                    replica_degrade_at=(0,), replica_fault_after=6)))
        return cfg

    def drive(cfg, n_each):
        """n_each requests per bucket through the router; returns the
        router port + fleet handle context results."""
        with Fleet(cfg, 2) as fleet:
            fleet.start()
            fleet.wait_ready(min_ready=2, timeout_s=120)
            router, httpd, port = _start_router(cfg, fleet)
            try:
                for _ in range(n_each):
                    s1, _ = _post(port, still_body((30, 60)))
                    s2, _ = _post(port, still_body((60, 60)))
                    assert s1 == 200 and s2 == 200
                # quiesce: every replica scored everything it sampled
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    healths = [_get_json(r.port, "/healthz")[1]
                               for r in fleet.ready_replicas()]
                    if all(h["serve_quality_scored"]
                           + h.get("serve_quality_errors", 0)
                           >= h["serve_quality_sampled"]
                           for h in healths):
                        break
                    time.sleep(0.1)
                from test_obs_plane import _get_json_text

                _, metrics_text = _get_json_text(port, "/metrics")
                parsed = parse_prometheus(metrics_text)
                hists = [h["serve_quality_photo_hist"] for h in healths]
                verdicts = [h["serve_quality"] for h in healths]
            finally:
                router.draining = True
                httpd.shutdown()
                httpd.server_close()
        return parsed, hists, verdicts

    # --- control fleet: exact merge + no drift anywhere --------------
    control_dir = tmp_path / "control"
    parsed, hists, verdicts = drive(quality_fleet_cfg(control_dir), 8)
    expect = merge_hists(hists)
    assert expect["count"] == 16  # every request sampled and scored
    cum = 0
    for bound, count in zip(expect["buckets_ms"], expect["counts"]):
        cum += count
        key = f'deepof_serve_quality_photo_bucket{{le="{_fmt(bound)}"}}'
        assert parsed[key] == cum, key
    assert parsed['deepof_serve_quality_photo_bucket{le="+Inf"}'] == 16
    assert parsed["deepof_serve_quality_scored"] == 16
    assert not any(v["exhausted"] for v in verdicts)
    # the router/Fleet were driven in-process: give the root dir the
    # (empty) metrics log run_fleet would have owned, so tail reads it
    (control_dir / "metrics.jsonl").touch()
    rc = cli_main(["tail", "--log-dir", str(control_dir), "--fleet"])
    assert rc == 0

    # --- degraded fleet: replica 0's weights corrupt mid-run ---------
    degraded_dir = tmp_path / "degraded"
    parsed, hists, verdicts = drive(
        quality_fleet_cfg(degraded_dir, degrade=True), 10)
    assert any(v["exhausted"] for v in verdicts), verdicts
    assert parsed["deepof_serve_quality_breaches"] >= 1
    (degraded_dir / "metrics.jsonl").touch()
    rc = cli_main(["tail", "--log-dir", str(degraded_dir), "--fleet"])
    assert rc == 7


def _fmt(bound: float) -> str:
    f = float(bound)
    return repr(int(f)) if f == int(f) else repr(f)
