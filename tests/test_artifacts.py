"""Executable artifact plane tests (DESIGN.md "Artifact plane",
serve/artifacts.py).

Unit tier: store publish/fetch round-trip with bitwise output parity,
atomic first-writer-wins publish, the integrity gates (tampered
manifest, tampered blob, drifted code, backend/version skew — every one
refuses to load, falls back to compile, and counts), the stdlib-only
verify/gc half, the jax-free `deepof_tpu artifacts` CLI verb's rc
contract (0 ok / 1 corrupt / 2 empty — verify-ckpt's convention), and
ledger_diff treating an artifact load as a non-recompile.

Slow tier: `warmup --serve` publishes the bucket x tier ladder and a
cold engine boots with ONLY artifact_hit rows, its flows bitwise equal
to the compile-path engine's on identical requests.

Chaos tier (slow, subprocess): a REAL-model fleet with the store on —
SIGKILL the scale-up replica mid-boot, the supervisor respawns it, every
request resolves via failover, and the respawned replica's ledger shows
it booted from artifacts (zero "aot" rows fleet-wide).

r17 executable index tier: the trace-free resolution plane — pure key
algebra (resolution_key / serve_config_digest / params_aval_sig),
atomic index publish + tolerant load, the resolve() trust gates (forged
entry, stale target, cross-wired name, version skew, tampered payload —
every one a loud counted reject), roots-pinned GC with index pruning,
supervisor GC wiring, the index-boot engine (only index_hit rows — zero
trace/lower on the resolve path), config-drift miss + fallback, the
deep-verify demote drill, and `artifacts verify --deep`'s rc contract.
"""

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
import zlib

import numpy as np
import pytest

from deepof_tpu.serve.artifacts import (BLOB, INDEX, MANIFEST, gc_store,
                                        index_targets, load_index,
                                        resolution_key,
                                        serve_config_digest, store_entries,
                                        verify_entry, verify_store,
                                        write_index)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------- helpers


def _store(tmp_path, backend="cpu"):
    from deepof_tpu.serve.artifacts import ArtifactStore

    return ArtifactStore(str(tmp_path / "exec"), backend=backend)


def _ledger(tmp_path, name="run"):
    from deepof_tpu.obs.ledger import ExecutableLedger

    return ExecutableLedger(str(tmp_path / name), backend="cpu")


def _tiny_lower():
    """A lowering cheap enough for the unit tier: elementwise jit."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x, y: (x @ y + 1.0, y * 2.0))
    a = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    return lambda: f.lower(a, a)


def _fake_entry(root: str, fp: str, payload: bytes = b"x" * 64,
                **manifest_overrides) -> None:
    """A hand-built store entry (stdlib only — no jax) whose manifest is
    self-consistent unless an override breaks it on purpose."""
    d = os.path.join(root, fp)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, BLOB), "wb") as f:
        f.write(payload)
    man = {"schema": 1, "fingerprint": fp, "name": "fake",
           "backend": "cpu", "jax": "0.0.0", "compile_s": 0.1,
           "created": 123.0,
           "payload": {"file": BLOB, "size": len(payload),
                       "crc32": zlib.crc32(payload) & 0xFFFFFFFF}}
    man.update(manifest_overrides)
    with open(os.path.join(d, MANIFEST), "w") as f:
        json.dump(man, f)


# ------------------------------------------------------ stdlib half


def test_verify_store_and_gc_stdlib_only(tmp_path):
    """The jax-free half the CLI verb rides: structural verification
    (schema, fingerprint-vs-dirname, payload size, crc32) and gc of
    corrupt + abandoned-tmp entries, valid ones kept."""
    root = str(tmp_path / "exec")
    _fake_entry(root, "a" * 16)
    _fake_entry(root, "b" * 16)
    os.makedirs(os.path.join(root, ".tmp-999-deadbeef"))
    # corrupt b: flip payload bytes without updating the manifest crc
    with open(os.path.join(root, "b" * 16, BLOB), "wb") as f:
        f.write(b"y" * 64)

    rep = verify_store(root)
    assert rep["total"] == 2 and rep["ok"] == 1
    assert rep["corrupt"] == ["b" * 16]
    assert rep["tmp_dirs"] == [".tmp-999-deadbeef"]
    good = verify_entry(root, "a" * 16)
    assert good["ok"] and good["name"] == "fake" and good["size"] == 64

    gc = gc_store(root)
    assert gc["removed"] == ["b" * 16]
    assert gc["kept"] == ["a" * 16]
    assert gc["tmp_removed"] == [".tmp-999-deadbeef"]
    assert store_entries(root) == ["a" * 16]


def test_verify_entry_catches_fingerprint_dirname_mismatch(tmp_path):
    """A manifest whose fingerprint disagrees with its directory name is
    corrupt — a renamed/copied entry must never verify."""
    root = str(tmp_path / "exec")
    _fake_entry(root, "c" * 16, fingerprint="d" * 16)
    ent = verify_entry(root, "c" * 16)
    assert not ent["ok"] and "fingerprint" in ent["why"]
    assert verify_store(root)["corrupt"] == ["c" * 16]


def test_gc_older_than_keeps_fresh_valid_entries(tmp_path):
    root = str(tmp_path / "exec")
    _fake_entry(root, "e" * 16, created=time.time())
    _fake_entry(root, "f" * 16, created=time.time() - 40 * 86400)
    gc = gc_store(root, older_than_days=30)
    assert gc["removed"] == ["f" * 16]
    assert gc["kept"] == ["e" * 16]


# ------------------------------------------------------- cli verb


def _cli(args, timeout=60):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run([sys.executable, "-m", "deepof_tpu", "artifacts",
                           *args], capture_output=True, text=True, env=env,
                          timeout=timeout)


def test_cli_artifacts_rc_contract(tmp_path):
    """`deepof_tpu artifacts` mirrors verify-ckpt's rc ladder: 2 on an
    empty store, 1 when any entry is corrupt, 0 when all verify; gc
    reports what it removed and exits 0. The verb is jax-free — it must
    answer fast even where jax can't import."""
    root = str(tmp_path / "exec")
    os.makedirs(root)
    r = _cli(["list", "--dir", root])
    assert r.returncode == 2 and "empty store" in r.stderr

    _fake_entry(root, "a" * 16)
    r = _cli(["verify", "--dir", root])
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout)
    assert rep["total"] == 1 and rep["ok"] == 1 and not rep["corrupt"]

    with open(os.path.join(root, "a" * 16, BLOB), "ab") as f:
        f.write(b"junk")
    r = _cli(["verify", "--dir", root])
    assert r.returncode == 1
    assert json.loads(r.stdout)["corrupt"] == ["a" * 16]

    r = _cli(["gc", "--dir", root])
    assert r.returncode == 0
    assert json.loads(r.stdout)["removed"] == ["a" * 16]
    r = _cli(["list", "--dir", root])
    assert r.returncode == 2


# ------------------------------------------------- store round-trip


def test_publish_fetch_roundtrip_bitwise_parity(tmp_path):
    """The tentpole's core loop: record_aot publishes nothing itself —
    the store's publish/fetch round-trips a serialized executable whose
    outputs are BITWISE equal to the in-process compile's, the hit is
    ledgered as compile_kind="artifact" + cache_verdict="artifact_hit",
    and the artifact row's resolve_s (fetch+deserialize) is what the
    acquisition figures are built from."""
    from deepof_tpu.obs.ledger import ROW_KEYS

    store = _store(tmp_path)
    lower = _tiny_lower()
    led = _ledger(tmp_path, "a")
    compiled, row = led.record_aot("demo", lower, artifacts=store)
    assert row["compile_kind"] == "aot"
    assert tuple(row.keys()) == ROW_KEYS
    assert store.publish(row["fingerprint"], compiled,
                         name="demo") == "published"
    # first-writer-wins: a second publish is a no-op, not a corruption
    assert store.publish(row["fingerprint"], compiled) == "exists"

    led2 = _ledger(tmp_path, "b")
    c2, row2 = led2.record_aot("demo", lower, artifacts=store)
    assert row2["compile_kind"] == "artifact"
    assert row2["cache_verdict"] == "artifact_hit"
    assert row2["resolve_s"] is not None
    st = led2.stats()
    assert st["exec_artifact_hits"] == 1
    assert st["exec_artifact_misses"] == 0

    x = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 8).astype(np.float32)
    for a, b in zip(compiled(x, y), c2(x, y)):
        assert (np.asarray(a) == np.asarray(b)).all()


def test_tampered_blob_and_manifest_refuse_to_load(tmp_path, capsys):
    """Both tamper axes: a crc-broken blob and a fingerprint-forged
    manifest each REJECT (loud stderr warn), fall back to compile, and
    count in exec_artifact_rejects — a stale artifact can never load."""
    store = _store(tmp_path)
    lower = _tiny_lower()
    led = _ledger(tmp_path, "a")
    compiled, row = led.record_aot("demo", lower, artifacts=store)
    fp = row["fingerprint"]
    store.publish(fp, compiled)

    blob = os.path.join(store.root, fp, BLOB)
    data = open(blob, "rb").read()
    with open(blob, "wb") as f:
        f.write(data[:-4] + b"XXXX")
    led2 = _ledger(tmp_path, "b")
    c2, row2 = led2.record_aot("demo", lower, artifacts=store)
    assert row2["compile_kind"] == "aot"  # fell back to compile
    assert led2.stats()["exec_artifact_rejects"] == 1
    assert "REJECT" in capsys.readouterr().err
    x = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    assert np.isfinite(np.asarray(c2(x, x)[0])).all()  # run completes

    with open(blob, "wb") as f:
        f.write(data)  # restore the blob, forge the manifest instead
    man_path = os.path.join(store.root, fp, MANIFEST)
    man = json.load(open(man_path))
    man["fingerprint"] = "0" * 16
    with open(man_path, "w") as f:
        json.dump(man, f)
    led3 = _ledger(tmp_path, "c")
    _, row3 = led3.record_aot("demo", lower, artifacts=store)
    assert row3["compile_kind"] == "aot"
    assert led3.stats()["exec_artifact_rejects"] == 1


def test_drifted_code_misses_and_falls_back(tmp_path):
    """The integrity gate is the fingerprint recomputed from the LOCAL
    lowering: code drift changes the fingerprint, so the stale artifact
    is simply never looked up — a miss, a compile, a counted fallback."""
    import jax
    import jax.numpy as jnp

    store = _store(tmp_path)
    led = _ledger(tmp_path, "a")
    compiled, row = led.record_aot("demo", _tiny_lower(), artifacts=store)
    store.publish(row["fingerprint"], compiled)

    drifted = jax.jit(lambda x, y: (x @ y + 2.0, y * 2.0))  # the "edit"
    a = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    led2 = _ledger(tmp_path, "b")
    _, row2 = led2.record_aot("demo", lambda: drifted.lower(a, a),
                              artifacts=store)
    assert row2["compile_kind"] == "aot"
    assert row2["fingerprint"] != row["fingerprint"]
    assert led2.stats()["exec_artifact_misses"] == 1
    assert led2.stats()["exec_artifact_hits"] == 0


def test_backend_skew_rejects(tmp_path):
    """An artifact serialized for another backend must refuse to load
    even when the fingerprint matches (the StableHLO is backend-neutral;
    the serialized executable is NOT)."""
    store = _store(tmp_path)
    led = _ledger(tmp_path, "a")
    compiled, row = led.record_aot("demo", _tiny_lower(), artifacts=store)
    fp = row["fingerprint"]
    store.publish(fp, compiled)
    man_path = os.path.join(store.root, fp, MANIFEST)
    man = json.load(open(man_path))
    man["backend"] = "tpu"
    with open(man_path, "w") as f:
        json.dump(man, f)
    got, verdict = store.fetch(fp)
    assert got is None and verdict.startswith("reject:")


def test_store_for_config_resolves_path_and_off_switch(tmp_path):
    """serve.artifacts_dir="" keeps the plane off (None store — the
    pre-r16 behavior byte-identical); a relative path resolves to an
    absolute root so replica cwd never decides which store boots."""
    from deepof_tpu.core.config import get_config
    from deepof_tpu.serve.artifacts import store_for_config

    cfg = get_config("flyingchairs")
    assert store_for_config(cfg) is None
    cwd = os.getcwd()
    try:
        os.chdir(tmp_path)
        cfg2 = cfg.replace(serve=dataclasses.replace(
            cfg.serve, artifacts_dir="rel/exec"))
        store = store_for_config(cfg2)
        assert os.path.isabs(store.root)
        assert store.root == os.path.join(str(tmp_path), "rel", "exec")
    finally:
        os.chdir(cwd)


# -------------------------------------------------- ledger provenance


def test_ledger_diff_artifact_load_is_not_a_recompile(tmp_path):
    """The r15 sentinel must not rc-8 a replica that booted from the
    store: the baseline's cache-hit row vs a live artifact row (zero
    persistent-cache activity) is a FETCH, not a recompile."""
    from deepof_tpu.obs.ledger import diff_ledgers, lowering_row

    base = lowering_row("serve_64x64_f32", compile_s=1.0,
                        compile_kind="aot",
                        cache={"requests": 1, "hits": 1, "misses": 0})
    live = lowering_row("serve_64x64_f32", compile_s=0.01,
                        compile_kind="artifact",
                        cache={"requests": 1, "hits": 0, "misses": 1},
                        cache_verdict="artifact_hit")
    rep = diff_ledgers([base], [live])
    assert rep["unexpected_recompiles"] == []
    assert not rep["failed"], rep

    # control: the same cache shape WITHOUT the artifact kind still
    # trips the sentinel — the guard is the kind, not a blanket skip
    live_miss = lowering_row("serve_64x64_f32", compile_s=1.0,
                             compile_kind="aot",
                             cache={"requests": 1, "hits": 0, "misses": 1})
    rep2 = diff_ledgers([base], [live_miss])
    assert rep2["unexpected_recompiles"], rep2


# --------------------------------------------------- slow: full ladder


@pytest.mark.slow
def test_warmup_publishes_ladder_then_cold_engine_boots_from_store(
        tmp_path):
    """The r16 acceptance, in-process: `warmup --serve` publishes the
    full bucket x tier ladder into the store (single writer), a cold
    engine (cleared jax caches, index OFF — the fingerprint boot path
    kept for continuity; the r17 index path has its own test below)
    warms with ONLY artifact hits — zero compiles — and serves flows
    BITWISE equal to a compile-path engine's on identical requests at
    the same bucket/tier."""
    import jax
    import jax.numpy as jnp

    from deepof_tpu.core.config import get_config
    from deepof_tpu.serve.engine import InferenceEngine, build_serve_model
    from deepof_tpu.train import warmup

    buckets = ((32, 64),)
    tiers = ("f32", "bf16")
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=(32, 64), gt_size=(32, 64)),
        serve=dataclasses.replace(cfg.serve, max_batch=2,
                                  batch_timeout_ms=40.0, buckets=buckets,
                                  precisions=tiers,
                                  artifacts_dir=str(tmp_path / "exec")),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6),
                                  log_dir=str(tmp_path / "run")))

    rep = warmup.warmup_serve(cfg)
    ladder = len(buckets) * len(tiers)
    assert rep["artifacts"]["published"] == ladder
    assert rep["artifacts"]["errors"] == 0
    assert all(b["artifact"] == "published" for b in rep["buckets"])
    assert verify_store(str(tmp_path / "exec"))["ok"] == ladder

    model = build_serve_model(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 32, 64, 6)))["params"]
    rng = np.random.RandomState(0)
    reqs = [(rng.randint(1, 255, (30, 60, 3), dtype=np.uint8),
             rng.randint(1, 255, (30, 60, 3), dtype=np.uint8), t)
            for t in tiers]

    jax.clear_caches()  # the cold scaled-up replica (fingerprint path)
    cfg_fp = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                   artifacts_index=False))
    with InferenceEngine(cfg_fp, model_params=(model, params)) as eng:
        eng.warm()
        st = eng.stats()
        assert st["exec_artifact_hits"] >= ladder, st
        assert st["exec_artifact_misses"] == 0, st
        assert st["exec_artifact_rejects"] == 0, st
        flows_art = [eng.submit(p, n, precision=t).result(timeout=300)
                     ["flow"] for p, n, t in reqs]
    # ledger provenance: the cold boot wrote ONLY artifact rows
    kinds = [json.loads(line).get("compile_kind")
             for line in open(tmp_path / "run" / "ledger.jsonl")]
    assert kinds.count("artifact") >= ladder
    # the publish pass wrote the "aot" rows; the cold boot none
    assert kinds.count("aot") == ladder

    jax.clear_caches()  # the compile-path control engine
    cfg_off = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                    artifacts_dir=""))
    with InferenceEngine(cfg_off, model_params=(model, params)) as eng:
        eng.warm()
        flows_cmp = [eng.submit(p, n, precision=t).result(timeout=300)
                     ["flow"] for p, n, t in reqs]
    for fa, fc in zip(flows_art, flows_cmp):
        assert fa.dtype == fc.dtype
        assert (fa == fc).all(), "artifact executable diverged bitwise"


# --------------------------------------------- r17: executable index


def _index_entry(name, fp, backend="cpu", jax_version=None,
                 config_digest="d" * 16, aval_sig="s" * 16, **overrides):
    """A well-formed index entry plus its honest resolution key."""
    if jax_version is None:
        import jax

        jax_version = jax.__version__
    ent = {"name": name, "fingerprint": fp,
           "config_digest": config_digest, "aval_sig": aval_sig,
           "backend": backend, "jax": jax_version, "created": 123.0}
    ent.update(overrides)
    key = resolution_key(ent["name"], ent["config_digest"],
                         ent["aval_sig"], ent["backend"], ent["jax"])
    return key, ent


def test_resolution_key_and_config_digest_are_pure():
    """jax-free key algebra: deterministic, sensitive to every
    component; the config digest covers exactly the lowering-relevant
    subset — replica plumbing (ports, log dirs, store paths) must NOT
    flip it, while anything that shapes the lattice must."""
    k = resolution_key("n", "d" * 16, "s" * 16, "cpu", "1.0")
    assert k == resolution_key("n", "d" * 16, "s" * 16, "cpu", "1.0")
    assert len(k) == 16 and all(c in "0123456789abcdef" for c in k)
    others = [resolution_key("m", "d" * 16, "s" * 16, "cpu", "1.0"),
              resolution_key("n", "e" * 16, "s" * 16, "cpu", "1.0"),
              resolution_key("n", "d" * 16, "t" * 16, "cpu", "1.0"),
              resolution_key("n", "d" * 16, "s" * 16, "tpu", "1.0"),
              resolution_key("n", "d" * 16, "s" * 16, "cpu", "2.0")]
    assert len({k, *others}) == 6

    from deepof_tpu.core.config import get_config

    cfg = get_config("flyingchairs")
    base = serve_config_digest(cfg)
    runtime = cfg.replace(
        train=dataclasses.replace(cfg.train, log_dir="/elsewhere"),
        serve=dataclasses.replace(
            cfg.serve, port=9999, artifacts_dir="/some/store",
            fleet=dataclasses.replace(cfg.serve.fleet, replicas=7)))
    assert serve_config_digest(runtime) == base
    assert serve_config_digest(cfg.replace(width_mult=0.5)) != base
    assert serve_config_digest(cfg.replace(serve=dataclasses.replace(
        cfg.serve, max_batch=cfg.serve.max_batch + 1))) != base


def test_index_write_is_atomic_merge_and_load_is_tolerant(tmp_path):
    """write_index merges over the existing index through a tmp-file +
    rename (no torn reader window, no staging left behind); load_index
    treats an absent/torn/wrong-schema index as EMPTY — on the boot
    path that is a miss, never an exception."""
    root = str(tmp_path / "exec")
    k1, e1 = _index_entry("a", "1" * 16)
    write_index(root, {k1: e1})
    k2, e2 = _index_entry("b", "2" * 16)
    idx = write_index(root, {k2: e2})
    assert set(idx["entries"]) == {k1, k2}  # merge, not replace
    assert load_index(root)["entries"][k1]["fingerprint"] == "1" * 16
    assert index_targets(root) == {"1" * 16, "2" * 16}
    assert not [n for n in os.listdir(root) if n.startswith(".tmp-")]

    with open(os.path.join(root, INDEX), "w") as f:
        f.write('{"schema": 1, "entries": {"x": ')  # torn mid-write
    assert load_index(root)["entries"] == {}
    with open(os.path.join(root, INDEX), "w") as f:
        json.dump({"schema": 99, "entries": {}}, f)
    assert load_index(root)["entries"] == {}
    assert index_targets(os.path.join(root, "missing")) == set()


def test_index_resolve_roundtrip_counts_and_row(tmp_path):
    """record_index: an honest entry resolves trace-free (fetch +
    deserialize only), writes the cache_verdict="index_hit" row
    carrying the INDEX's fingerprint, queues one deep-verify slot, and
    the resolved executable's outputs are bitwise equal to the
    compile-path one's. A drifted config is a DIFFERENT key: a clean
    counted miss, no row."""
    store = _store(tmp_path)
    led = _ledger(tmp_path, "a")
    compiled, row = led.record_aot("demo", _tiny_lower(), artifacts=store)
    store.publish(row["fingerprint"], compiled, name="demo")
    key, ent = _index_entry("demo", row["fingerprint"])
    write_index(store.root, {key: ent})

    led2 = _ledger(tmp_path, "b")
    c2, row2, verdict = led2.record_index("demo", _store(tmp_path), key)
    assert verdict == "index_hit"
    assert row2["compile_kind"] == "artifact"
    assert row2["cache_verdict"] == "index_hit"
    assert row2["fingerprint"] == row["fingerprint"]
    assert row2["resolve_s"] is not None
    st = led2.stats()
    assert st["exec_index_hits"] == 1 and st["exec_index_misses"] == 0
    assert st["exec_index_rejects"] == 0
    assert st["exec_deep_verify_pending"] == 1
    led2.note_deep_verify(True)
    st = led2.stats()
    assert st["exec_deep_verify_pending"] == 0
    assert st["exec_deep_verify_ok"] == 1

    x = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 8).astype(np.float32)
    for a, b in zip(compiled(x, y), c2(x, y)):
        assert (np.asarray(a) == np.asarray(b)).all()

    k_drift, _ = _index_entry("demo", row["fingerprint"],
                              config_digest="f" * 16)
    c3, row3, verdict3 = led2.record_index("demo", _store(tmp_path),
                                           k_drift)
    assert (c3, row3, verdict3) == (None, None, "index_miss")
    assert led2.stats()["exec_index_misses"] == 1


def test_index_trust_gates_reject_loudly(tmp_path, capsys):
    """Every poisoned-index case REFUSES to serve, warns on stderr, and
    counts in exec_index_rejects: a forged entry (components do not
    hash back to the key), a stale target (entry outlived its
    executable), a cross-wired target (manifest name disagrees), a
    version-skewed entry, and a tampered payload behind an honest
    entry. None of them raises — the caller falls back to the lowering
    path."""
    store = _store(tmp_path)
    led = _ledger(tmp_path, "a")
    compiled, row = led.record_aot("demo", _tiny_lower(), artifacts=store)
    fp = row["fingerprint"]
    store.publish(fp, compiled, name="demo")
    led2 = _ledger(tmp_path, "b")

    def resolve(key):
        return led2.record_index("demo", _store(tmp_path), key)[2]

    # forged: key hashed over name "demo", entry claims another name
    key, ent = _index_entry("demo", fp)
    write_index(store.root, {key: dict(ent, name="other")})
    assert resolve(key) == "index_reject:entry_forged"

    # stale target: honest entry, executable no longer in the store
    k2, e2 = _index_entry("demo", "0" * 16)
    write_index(store.root, {k2: e2})
    assert resolve(k2) == "index_reject:stale_target"

    # cross-wired: honest entry under another name pointing at demo's
    # artifact — the target manifest's recorded name disagrees
    k3, e3 = _index_entry("other", fp)
    write_index(store.root, {k3: e3})
    assert resolve(k3) == "index_reject:name_mismatch"

    # version skew: entry lowered under another jax
    k4, e4 = _index_entry("demo", fp, jax_version="0.0.0")
    write_index(store.root, {k4: e4})
    assert resolve(k4) == "index_reject:jax_version_mismatch"

    # tampered payload behind an honest entry: the fetch gates fire
    write_index(store.root, {key: ent})
    blob = os.path.join(store.root, fp, BLOB)
    data = open(blob, "rb").read()
    with open(blob, "wb") as f:
        f.write(data[:-4] + b"XXXX")
    assert resolve(key).startswith("index_reject:target_")

    st = led2.stats()
    assert st["exec_index_rejects"] == 5
    assert st["exec_index_hits"] == 0
    assert "INDEX REJECT" in capsys.readouterr().err


def test_gc_pins_roots_and_index_targets_and_prunes_stale(tmp_path):
    """Retirement-path GC safety: live-lattice roots and the index's
    own targets are pinned against the age sweep; a corrupt entry goes
    regardless and its index entries are PRUNED (a later boot takes a
    clean miss, not a stale-target reject); leftover `.tmp-*-index.json`
    staging FILES are swept like tmp dirs."""
    root = str(tmp_path / "exec")
    old = time.time() - 40 * 86400
    _fake_entry(root, "a" * 16, created=old)  # pinned via roots
    _fake_entry(root, "b" * 16, created=old)  # pinned via the index
    _fake_entry(root, "c" * 16, created=old)  # unpinned: swept by age
    _fake_entry(root, "e" * 16, created=old)  # corrupt: goes regardless
    with open(os.path.join(root, "e" * 16, BLOB), "wb") as f:
        f.write(b"tampered" * 8)
    kb, eb = _index_entry("fake", "b" * 16)
    ke, ee = _index_entry("fake2", "e" * 16, aval_sig="t" * 16)
    write_index(root, {kb: eb, ke: ee})
    with open(os.path.join(root, ".tmp-42-index.json"), "w") as f:
        f.write("{}")

    gc = gc_store(root, older_than_days=30, roots={"a" * 16})
    assert sorted(gc["removed"]) == ["c" * 16, "e" * 16]
    assert sorted(gc["kept"]) == ["a" * 16, "b" * 16]
    assert ".tmp-42-index.json" in gc["tmp_removed"]
    assert gc["index_pruned"] == [ke]
    assert set(load_index(root)["entries"]) == {kb}
    assert not os.path.exists(os.path.join(root, ".tmp-42-index.json"))


def test_fleet_retirement_gc_wiring(tmp_path):
    """Satellite 1: the supervisor's retirement hook sweeps the store
    with every replica ledger's fingerprints as roots (index targets
    pinned inside gc_store) and logs one warn record into the fleet's
    metrics.jsonl — exercised directly, no processes spawned."""
    from deepof_tpu.core.config import get_config
    from deepof_tpu.serve.fleet import Fleet

    store_root = str(tmp_path / "exec")
    old = time.time() - 40 * 86400
    _fake_entry(store_root, "a" * 16, created=old)  # a live ledger's fp
    _fake_entry(store_root, "b" * 16, created=old)  # unpinned: swept
    fleet_dir = str(tmp_path / "fleet")
    rdir = os.path.join(fleet_dir, "replica-0")
    os.makedirs(rdir)
    with open(os.path.join(rdir, "ledger.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "lowering", "name": "x",
                            "fingerprint": "a" * 16}) + "\n")

    cfg = get_config("flyingchairs")
    cfg = cfg.replace(
        serve=dataclasses.replace(
            cfg.serve, artifacts_dir=store_root,
            fleet=dataclasses.replace(cfg.serve.fleet,
                                      artifacts_gc_days=30.0)),
        train=dataclasses.replace(cfg.train, log_dir=fleet_dir))
    fleet = Fleet(cfg, 1)
    fleet._artifacts_gc("test")
    assert store_entries(store_root) == ["a" * 16]
    recs = [json.loads(line)
            for line in open(os.path.join(fleet_dir, "metrics.jsonl"))]
    assert any("artifacts gc" in r.get("message", "") for r in recs)


@pytest.mark.slow
def test_index_boot_is_trace_free_and_bitwise_equal(tmp_path):
    """The r17 tentpole acceptance, in-process: `warmup --serve` writes
    the executable index, a cold engine resolves the WHOLE ladder
    through it — ledger provenance shows ONLY index_hit rows on the
    resolve path (zero "aot", zero untagged lowerings; deep-verify rows
    are the off-path integrity plane, which confirms every entry) —
    and serves flows bitwise equal to the compile-path engine's. A
    config drift (different lowering-relevant subset) flips the
    resolution key: the index MISSES and the engine falls back to the
    compile path, loudly counted."""
    import jax
    import jax.numpy as jnp

    from deepof_tpu.core.config import get_config
    from deepof_tpu.serve.engine import InferenceEngine, build_serve_model
    from deepof_tpu.train import warmup

    tiers = ("f32", "bf16")
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=(32, 64), gt_size=(32, 64)),
        serve=dataclasses.replace(cfg.serve, max_batch=2,
                                  batch_timeout_ms=40.0,
                                  buckets=((32, 64),), precisions=tiers,
                                  artifacts_dir=str(tmp_path / "exec")),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6),
                                  log_dir=str(tmp_path / "publish")))
    rep = warmup.warmup_serve(cfg)
    ladder = len(rep["buckets"])
    assert rep["artifacts"]["index_entries"] == ladder

    model = build_serve_model(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 32, 64, 6)))["params"]
    rng = np.random.RandomState(0)
    reqs = [(rng.randint(1, 255, (30, 60, 3), dtype=np.uint8),
             rng.randint(1, 255, (30, 60, 3), dtype=np.uint8), t)
            for t in tiers]

    jax.clear_caches()  # the cold scaled-up replica (index path)
    cfg_cold = cfg.replace(train=dataclasses.replace(
        cfg.train, log_dir=str(tmp_path / "cold")))
    with InferenceEngine(cfg_cold, model_params=(model, params)) as eng:
        eng.warm()
        st = eng.stats()
        assert st["exec_index_hits"] >= ladder, st
        assert st["exec_index_misses"] == 0, st
        assert st["exec_index_rejects"] == 0, st
        # resolution never even reached the fingerprint path
        assert st["exec_artifact_hits"] == 0, st
        flows_idx = [eng.submit(p, n, precision=t).result(timeout=300)
                     ["flow"] for p, n, t in reqs]
        assert eng.deep_verify_join(timeout_s=300)
        st = eng.stats()
        assert st["exec_deep_verify_ok"] >= ladder, st
        assert st["exec_deep_verify_demoted"] == 0, st
        assert st["exec_deep_verify_pending"] == 0, st
    rows = [json.loads(line)
            for line in open(tmp_path / "cold" / "ledger.jsonl")]
    kinds = [r.get("compile_kind") for r in rows]
    assert kinds.count("artifact") >= ladder
    for r in rows:
        assert r.get("compile_kind") in (None, "artifact",
                                         "deep_verify"), r
        if r.get("compile_kind") == "artifact":
            assert r.get("cache_verdict") == "index_hit", r

    jax.clear_caches()  # the compile-path control engine
    cfg_off = cfg.replace(
        serve=dataclasses.replace(cfg.serve, artifacts_dir=""),
        train=dataclasses.replace(cfg.train,
                                  log_dir=str(tmp_path / "control")))
    with InferenceEngine(cfg_off, model_params=(model, params)) as eng:
        eng.warm()
        flows_cmp = [eng.submit(p, n, precision=t).result(timeout=300)
                     ["flow"] for p, n, t in reqs]
    for fa, fc in zip(flows_idx, flows_cmp):
        assert fa.dtype == fc.dtype
        assert (fa == fc).all(), "index executable diverged bitwise"

    # config drift: a bigger max_batch lowers different avals — the
    # key changes, the index misses, the compile path takes over
    jax.clear_caches()
    cfg_drift = cfg.replace(
        serve=dataclasses.replace(cfg.serve, max_batch=3),
        train=dataclasses.replace(cfg.train,
                                  log_dir=str(tmp_path / "drift")))
    with InferenceEngine(cfg_drift, model_params=(model, params)) as eng:
        eng.warm()
        st = eng.stats()
        assert st["exec_index_misses"] >= ladder, st
        assert st["exec_index_hits"] == 0, st
    kinds = [json.loads(line).get("compile_kind")
             for line in open(tmp_path / "drift" / "ledger.jsonl")]
    assert kinds.count("aot") >= ladder  # loud fallback, not silence


@pytest.mark.slow
def test_deep_verify_demotes_cross_wired_index_entry(tmp_path):
    """The deferred integrity plane: cross-wire the f32 cold entry to
    the bf16 tier's artifact with the target manifest's name forged to
    match — every boot-path gate passes, so the engine serves the
    stale index hit. The background deep verify re-lowers, sees the
    fingerprint mismatch, DEMOTES loudly (counter + ledger row) and
    swaps in a fresh compile; requests after the swap produce flows
    bitwise equal to the compile path's."""
    import jax
    import jax.numpy as jnp

    from deepof_tpu.core.config import get_config
    from deepof_tpu.serve.engine import InferenceEngine, build_serve_model
    from deepof_tpu.train import warmup

    store_root = str(tmp_path / "exec")
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=(32, 64), gt_size=(32, 64)),
        serve=dataclasses.replace(cfg.serve, max_batch=2,
                                  batch_timeout_ms=40.0,
                                  buckets=((32, 64),),
                                  precisions=("f32", "bf16"),
                                  artifacts_dir=store_root),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6),
                                  log_dir=str(tmp_path / "publish")))
    warmup.warmup_serve(cfg)

    # the poisoning: f32's entry now claims bf16's artifact, and the
    # target manifest is forged to agree on the name
    idx = load_index(store_root)
    by_name = {e["name"]: (k, e) for k, e in idx["entries"].items()}
    (k_f32, e_f32), = [v for n, v in by_name.items()
                       if n.endswith(":f32:cold")]
    (_, e_bf16), = [v for n, v in by_name.items()
                    if n.endswith(":bf16:cold")]
    victim_fp = e_bf16["fingerprint"]
    assert victim_fp != e_f32["fingerprint"]
    write_index(store_root, {k_f32: dict(e_f32, fingerprint=victim_fp)})
    man_path = os.path.join(store_root, victim_fp, MANIFEST)
    man = json.load(open(man_path))
    man["name"] = e_f32["name"]
    with open(man_path, "w") as f:
        json.dump(man, f)

    model = build_serve_model(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 32, 64, 6)))["params"]
    jax.clear_caches()
    cfg_cold = cfg.replace(train=dataclasses.replace(
        cfg.train, log_dir=str(tmp_path / "cold")))
    with InferenceEngine(cfg_cold, model_params=(model, params)) as eng:
        eng.warm()
        st = eng.stats()
        assert st["exec_index_hits"] >= 1, st  # the poisoned hit served
        assert eng.deep_verify_join(timeout_s=300)
        st = eng.stats()
        assert st["exec_deep_verify_demoted"] == 1, st
        assert st["exec_deep_verify_pending"] == 0, st
        # after the swap: a real f32 request through the replacement
        rng = np.random.RandomState(0)
        prev = rng.randint(1, 255, (30, 60, 3), dtype=np.uint8)
        nxt = rng.randint(1, 255, (30, 60, 3), dtype=np.uint8)
        flow = eng.submit(prev, nxt, precision="f32").result(
            timeout=300)["flow"]
    rows = [json.loads(line)
            for line in open(tmp_path / "cold" / "ledger.jsonl")]
    assert any(r.get("cache_verdict") == "deep_verify_demoted"
               for r in rows), [r.get("cache_verdict") for r in rows]

    jax.clear_caches()  # compile-path control for bitwise equality
    cfg_off = cfg.replace(
        serve=dataclasses.replace(cfg.serve, artifacts_dir=""),
        train=dataclasses.replace(cfg.train,
                                  log_dir=str(tmp_path / "control")))
    with InferenceEngine(cfg_off, model_params=(model, params)) as eng:
        flow_cmp = eng.submit(prev, nxt, precision="f32").result(
            timeout=300)["flow"]
    assert flow.dtype == flow_cmp.dtype
    assert (flow == flow_cmp).all(), "demote swap-in diverged bitwise"


@pytest.mark.slow
def test_cli_artifacts_verify_deep_rc_contract(tmp_path):
    """`deepof_tpu artifacts verify --deep` re-lowers the lattice under
    the given config and compares StableHLO fingerprints against the
    index across a PROCESS boundary (fingerprints must be stable or the
    whole plane is fiction): rc 0 when every indexed entry matches,
    rc 1 on drift (tampered index fingerprint), rc 2 when nothing is
    indexed."""
    import dataclasses as dc

    from deepof_tpu.core.config import get_config
    from deepof_tpu.train import warmup

    store_root = str(tmp_path / "exec")
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dc.replace(cfg.data, image_size=(32, 64), gt_size=(32, 64),
                        dataset="synthetic"),
        serve=dc.replace(cfg.serve, max_batch=2, buckets=((32, 64),),
                         precisions=("f32",), artifacts_dir=store_root),
        train=dc.replace(cfg.train, eval_amplifier=1.0,
                         eval_clip=(-1e6, 1e6),
                         log_dir=str(tmp_path / "publish")))
    warmup.warmup_serve(cfg)

    deep_args = ["verify", "--deep", "--dir", store_root,
                 "--model", "flownet_s",
                 "--set", "width_mult=0.25",
                 "--set", "data.image_size=(32,64)",
                 "--set", "data.gt_size=(32,64)",
                 "--set", "serve.max_batch=2",
                 "--set", "serve.buckets=((32,64),)",
                 "--set", "serve.precisions=('f32',)"]
    r = _cli(deep_args, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    rep = json.loads(r.stdout)
    assert rep["ok"] == rep["total"] >= 1
    assert rep["drift"] == [] and rep["unindexed"] == []

    # drift: tamper the indexed fingerprint — rc 1, the entry named
    idx = load_index(store_root)
    key, ent = next(iter(idx["entries"].items()))
    write_index(store_root, {key: dict(ent, fingerprint="9" * 16)})
    r = _cli(deep_args, timeout=300)
    assert r.returncode == 1, (r.stdout, r.stderr)
    rep = json.loads(r.stdout)
    assert rep["drift"] == [ent["name"]]

    # empty: no index at all — rc 2
    os.remove(os.path.join(store_root, INDEX))
    r = _cli(deep_args, timeout=300)
    assert r.returncode == 2, (r.stdout, r.stderr)


# ----------------------------------------------- slow chaos: the drill


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_chaos_scale_up_sigkill_respawns_from_artifacts(tmp_path):
    """The fleet drill with the store ON and REAL-model replicas:
    publish the ladder, checkpoint the params, start a 1-replica fleet,
    drive load, scale up, SIGKILL the new replica mid-boot. The
    supervisor respawns it, 100% of requests resolve via failover to
    the surviving replica, and the respawned replica's ledger proves it
    booted from artifacts — zero "aot" rows anywhere in the fleet."""
    import base64

    import jax
    import jax.numpy as jnp

    cv2 = pytest.importorskip("cv2")

    from deepof_tpu.core.config import get_config
    from deepof_tpu.serve.engine import build_serve_model
    from deepof_tpu.serve.fleet import Fleet
    from deepof_tpu.serve.router import Router, build_router_server
    from deepof_tpu.train import warmup
    from deepof_tpu.train.checkpoint import CheckpointManager
    from deepof_tpu.train.schedule import step_decay_schedule
    from deepof_tpu.train.state import create_train_state, make_optimizer

    fleet_dir = tmp_path / "fleet"
    store_dir = str(tmp_path / "exec")
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=(32, 64), gt_size=(32, 64)),
        serve=dataclasses.replace(
            cfg.serve, max_batch=2, batch_timeout_ms=20.0,
            buckets=((32, 64),), precisions=("f32",),
            fake_exec_ms=None,  # REAL replicas: the artifact plane's case
            host="127.0.0.1", port=0, artifacts_dir=store_dir,
            fleet=dataclasses.replace(
                cfg.serve.fleet, poll_s=0.2, stale_after_s=10.0,
                spawn_timeout_s=180.0, term_grace_s=1.0, backoff_s=0.2,
                backoff_max_s=1.0, healthy_after_s=60.0,
                proxy_timeout_s=30.0, max_in_flight=16,
                drain_timeout_s=2.0)),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6),
                                  log_dir=str(fleet_dir)),
        obs=dataclasses.replace(cfg.obs, heartbeat_period_s=0.2,
                                watchdog_min_s=3600.0))

    # single-writer publish (the `warmup --serve` leg)
    pub_cfg = cfg.replace(train=dataclasses.replace(
        cfg.train, log_dir=str(tmp_path / "publish")))
    rep = warmup.warmup_serve(pub_cfg)
    assert rep["artifacts"]["published"] >= 1

    # the checkpoint every replica restores (restore_params' template)
    model = build_serve_model(cfg)
    tx = make_optimizer(cfg.optim, step_decay_schedule(cfg.optim, 1))
    for idx in range(3):  # pre-seed replica dirs with the shared ckpt
        rdir = fleet_dir / f"replica-{idx}"
        rdir.mkdir(parents=True, exist_ok=True)
        if idx == 0:
            state = create_train_state(model, jnp.zeros((1, 32, 64, 6)),
                                       tx, seed=0)
            mgr = CheckpointManager(str(rdir / "ckpt"), async_save=False)
            mgr.save(state)
            mgr.finalize()
        else:
            os.symlink(str(fleet_dir / "replica-0" / "ckpt"),
                       str(rdir / "ckpt"))

    def _body(rng):
        imgs = []
        for _ in range(2):
            ok, buf = cv2.imencode(".png", rng.randint(
                1, 255, (30, 60, 3), dtype=np.uint8))
            assert ok
            imgs.append(base64.b64encode(buf.tobytes()).decode())
        return json.dumps({"prev": imgs[0], "next": imgs[1]}).encode()

    rng = np.random.RandomState(0)
    bodies = [_body(rng) for _ in range(4)]
    outcomes: list = []
    lock = threading.Lock()

    def _post(port, body):
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            conn.request("POST", "/v1/flow", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    with Fleet(cfg, 1) as fleet:
        fleet.start()
        fleet.wait_ready(min_ready=1, timeout_s=180)
        router = Router(cfg, fleet)
        fleet.on_retired = router.retire_slot
        httpd = build_router_server(cfg, router)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        port = httpd.server_address[1]
        stop = threading.Event()

        def _load():
            i = 0
            while not stop.is_set():
                try:
                    status, payload = _post(port, bodies[i % len(bodies)])
                except Exception as e:  # noqa: BLE001 - a drop is a bug
                    status, payload = -1, str(e).encode()
                with lock:
                    outcomes.append((status, payload))
                i += 1

        loader = threading.Thread(target=_load, daemon=True)
        loader.start()
        try:
            new_idx = fleet.scale_up()
            assert new_idx is not None
            # SIGKILL the scale-up replica mid-boot (before ready)
            deadline = time.monotonic() + 60
            killed = False
            while time.monotonic() < deadline and not killed:
                for d in fleet.describe():
                    if d["replica"] == new_idx and d["pid"]:
                        try:
                            os.kill(d["pid"], 9)
                            killed = True
                        except OSError:
                            pass
                        break
                if not killed:
                    time.sleep(0.05)
            assert killed, fleet.describe()
            # the supervisor respawns it and it reaches ready
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                if fleet.stats()["fleet_ready"] >= 2:
                    break
                time.sleep(0.2)
            stats = fleet.stats()
            assert stats["fleet_ready"] >= 2, stats
            assert stats["fleet_crashes"] + stats["fleet_respawns"] >= 1, \
                stats
            time.sleep(1.0)  # a beat of load on the respawned replica
        finally:
            stop.set()
            loader.join(timeout=30)
            router.draining = True
            httpd.shutdown()
            httpd.server_close()

    # 100% resolution: every request got a structured response (the
    # survivor absorbed the kill window via failover)
    assert outcomes
    bad = [(s, p[:120]) for s, p in outcomes if s != 200]
    assert not bad, (len(outcomes), bad[:5])

    # the respawned replica booted from the INDEX — trace-free: its
    # ledger has index_hit rows, and fleet-wide the only compile kinds
    # anywhere are "artifact" (index/fingerprint resolution) and
    # "deep_verify" (the background integrity plane, off the boot
    # path) — zero "aot" rows, zero untagged lowerings
    new_ledger = fleet_dir / f"replica-{new_idx}" / "ledger.jsonl"
    rows = [json.loads(line) for line in open(new_ledger)]
    kinds = [r.get("compile_kind") for r in rows]
    assert kinds.count("artifact") >= 1, kinds
    assert any(r.get("cache_verdict") == "index_hit" for r in rows), \
        [(r.get("compile_kind"), r.get("cache_verdict")) for r in rows]
    for rdir in sorted(fleet_dir.glob("replica-*")):
        lp = rdir / "ledger.jsonl"
        if lp.exists():
            for line in open(lp):
                k = json.loads(line).get("compile_kind")
                assert k in (None, "artifact", "deep_verify"), \
                    f"{rdir.name} compiled ({k}) instead of fetching"
