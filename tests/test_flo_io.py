import numpy as np
import pytest

from deepof_tpu.io import read_flo, write_flo, FLO_TAG


def test_roundtrip(tmp_path, rng):
    flow = rng.randn(17, 23, 2).astype(np.float32) * 20
    p = tmp_path / "a.flo"
    write_flo(p, flow)
    out = read_flo(p)
    np.testing.assert_array_equal(out, flow)


def test_header_layout(tmp_path):
    """Middlebury layout: float32 tag, int32 w, int32 h, then u,v interleaved."""
    flow = np.zeros((2, 3, 2), np.float32)
    flow[0, 1] = (5.0, -7.0)
    p = tmp_path / "b.flo"
    write_flo(p, flow)
    raw = p.read_bytes()
    assert np.frombuffer(raw[:4], np.float32)[0] == np.float32(FLO_TAG)
    w, h = np.frombuffer(raw[4:12], np.int32)
    assert (w, h) == (3, 2)
    data = np.frombuffer(raw[12:], np.float32).reshape(2, 3, 2)
    assert data[0, 1, 0] == 5.0 and data[0, 1, 1] == -7.0


def test_bad_tag(tmp_path):
    p = tmp_path / "c.flo"
    p.write_bytes(b"\x00" * 64)
    with pytest.raises(ValueError):
        read_flo(p)


def test_bad_shape(tmp_path):
    with pytest.raises(ValueError):
        write_flo(tmp_path / "d.flo", np.zeros((4, 4, 3), np.float32))
