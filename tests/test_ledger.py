"""Executable-ledger + perf-regression-sentinel tests (obs/ledger.py,
tools/ledger_diff.py, DESIGN.md "Executable ledger").

Pins the ISSUE 15 contract: every lowering becomes a provenance row
(stable StableHLO fingerprint, compile seconds, persistent-cache
hit/miss, XLA cost analysis, memory footprint, donation map) with the
frozen ROW_KEYS schema; diff_ledgers classifies drift into exactly four
failure classes whose verdicts over the recorded fixture
(tests/fixtures/ledger, make_ledger_fixture.py) are byte-pinned against
goldens; `tools/ledger_diff.py` and `deepof_tpu tail` map a failed
verdict to exit code 8 while a same-config warm rerun diffs clean; the
real engine path writes rows + the registry-declared exec_* stats block
(and with obs.ledger=false keeps the stats schema byte-identical to the
pre-ledger stack); obs/telemetry.py's step_flops/device_memory_summary
get their first direct unit coverage; and the bench_trend /
serve_bench --ledger report schemas hold.

Fast tier throughout: the jax-touching tests lower tiny elementwise
functions (milliseconds, no conv-net compile).
"""

import dataclasses
import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from deepof_tpu.obs.ledger import (DEFAULT_COMPILE_FACTOR, ROW_KEYS,
                                   ExecutableLedger, diff_ledgers,
                                   exec_name, fingerprint_text,
                                   latest_by_name, ledger_verdict,
                                   load_ledger, lowering_row,
                                   normalize_hlo, quality_exec_name,
                                   summarize_ledger)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURE = os.path.join(HERE, "fixtures", "ledger")
GOLDENS = os.path.join(HERE, "fixtures", "goldens")


def _golden(name: str):
    with open(os.path.join(GOLDENS, name)) as f:
        return json.load(f)


# ------------------------------------------------- fingerprint contract


def test_normalize_hlo_strips_location_metadata_only():
    """The fingerprint input drops `loc(...)` attributes and `#loc`
    lines — the one nondeterministic part of the printed module — and
    trailing whitespace, but keeps every computation-bearing token
    (shapes, dtypes, donation aliasing)."""
    body = ('module @jit_f {\n'
            '  func.func public @main(%arg0: tensor<8x8xf32> '
            '{tf.aliasing_output = 0 : i32}) -> tensor<8x8xf32> {\n'
            '    %0 = stablehlo.add %arg0, %arg0 : tensor<8x8xf32>\n'
            '    return %0 : tensor<8x8xf32>\n'
            '  }\n'
            '}')
    with_locs = (body.replace(
        ': tensor<8x8xf32>\n    return',
        ': tensor<8x8xf32> loc("add" "f.py":3:0)\n    return')
        + '\n#loc0 = loc("f.py":1:0)\n') .replace(
        '  }', '  }   ')  # trailing whitespace noise
    assert normalize_hlo(with_locs) == normalize_hlo(body)
    assert fingerprint_text(with_locs) == fingerprint_text(body)
    # the full debug-info grammar must strip too: loc(unknown), nested
    # callsite/fused forms, and quoted names that contain parens —
    # a debug-enabled run and its baseline must hash identically
    anchor = ": tensor<8x8xf32>\n    return"
    for loc in ("loc(unknown)",
                'loc(callsite("add"("f.py":3:0) at "g.py":9:1))',
                'loc(fused["a", "weird(name.py":7:0])',
                'loc("paren(in)name.py":1:2)'):
        deco = body.replace(anchor,
                            f": tensor<8x8xf32> {loc}\n    return")
        assert normalize_hlo(deco) == normalize_hlo(body), loc
        assert fingerprint_text(deco) == fingerprint_text(body), loc
    # ...while an identifier merely ending in "loc" is computation text
    assert "myloc(" in normalize_hlo("  %0 = myloc(%arg0)")
    # any computation change changes the fingerprint
    assert (fingerprint_text(body.replace("8x8", "16x16"))
            != fingerprint_text(body))


def test_fingerprint_stable_across_lowerings_and_sensitive_to_shape():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: jnp.tanh(x) * 2.0)
    a = jax.ShapeDtypeStruct((4, 4), jnp.float32)
    b = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    fp1 = fingerprint_text(f.lower(a).as_text())
    fp2 = fingerprint_text(f.lower(a).as_text())
    assert fp1 == fp2  # re-lowering the same avals is a pure function
    assert fingerprint_text(f.lower(b).as_text()) != fp1


# ------------------------------------------------------ row schema pins


def test_lowering_row_schema_cost_memory_and_donation():
    """One real (tiny) AOT lowering fills the frozen ROW_KEYS schema:
    fingerprint + cost analysis from the Lowered, memory_analysis from
    the Compiled, and the donation map from args_info."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x, y: (x @ y, y), donate_argnums=(0,))
    a = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    lowered = f.lower(a, a)
    row = lowering_row("demo", lowered=lowered, compiled=lowered.compile(),
                       compile_s=0.25, compile_kind="aot",
                       cache={"requests": 1, "hits": 0, "misses": 1},
                       backend="cpu")
    assert tuple(row.keys()) == ROW_KEYS  # the schema the fixture pins
    assert row["kind"] == "exec" and row["name"] == "demo"
    assert isinstance(row["fingerprint"], str) and len(row["fingerprint"]) == 16
    assert row["compile_s"] == 0.25
    assert row["compile_kind"] == "aot"
    assert row["cache_misses"] == 1 and row["cache_hits"] == 0
    assert row["flops"] and row["flops"] > 0  # 8x8 matmul ~ 2*8^3
    assert row["bytes_accessed"] and row["arith_intensity"] > 0
    assert row["roofline_s"] and row["roofline_s"] > 0
    assert row["donated_args"] == 1 and row["num_args"] == 2
    # cpu PJRT reports memory_analysis: argument/output/temp are ints
    assert isinstance(row["argument_bytes"], int)
    assert isinstance(row["output_bytes"], int)
    # a site with no Lowered/Compiled leaves every field None, never raises
    bare = lowering_row("bare")
    assert tuple(bare.keys()) == ROW_KEYS
    assert bare["fingerprint"] is None and bare["argument_bytes"] is None


def test_exec_names_are_the_shared_warmup_engine_contract():
    assert exec_name((32, 64), "f32", "cold") == "serve:32x64:f32:cold"
    assert quality_exec_name((32, 64)) == "quality:32x64"


# ------------------------------------------------- ledger record/stats


def test_ledger_records_counts_recompiles_and_flushes_timings(tmp_path):
    led = ExecutableLedger(str(tmp_path), backend="cpu")
    r1 = {"fingerprint": "aaaa", "compile_s": 1.0,
          "cache": {"requests": 1, "hits": 1, "misses": 0}}

    class _L:
        """Duck-typed Lowered: as_text only (cost analysis absent)."""

        def __init__(self, text):
            self._text = text

        def as_text(self):
            return self._text

        def cost_analysis(self):
            raise NotImplementedError

    led.record("train_step", lowered=_L("module A"), compile_s=1.0,
               cache=r1["cache"])
    # the SAME name lowering to a DIFFERENT module within one run is the
    # live recompile signal
    led.record("train_step", lowered=_L("module B"), compile_s=0.5,
               cache={"requests": 1, "hits": 0, "misses": 1})
    led.note_exec("train_step", 0.01)
    led.note_exec("train_step", 0.03)
    stats = led.stats()
    assert stats["exec_lowerings"] == 2
    assert stats["exec_recompiles"] == 1
    assert stats["exec_compile_s"] == 1.5
    assert stats["exec_cache_hits"] == 1
    assert stats["exec_cache_misses"] == 1
    assert stats["exec_executables"] == 1
    assert stats["exec_dispatches"] == 2
    assert stats["exec_dispatch_s"] == pytest.approx(0.04)
    assert stats["exec_fingerprints"]["train_step"] == fingerprint_text(
        "module B")
    led.flush()
    rows = load_ledger(str(tmp_path))
    assert [r["kind"] for r in rows] == ["exec", "exec", "exec_timing"]
    # newest row per name wins in the diff view
    assert latest_by_name(rows)["train_step"]["compile_s"] == 0.5
    s = summarize_ledger(rows)
    assert s["lowerings"] == 2 and s["recompiles"] == 1
    assert s["executables"] == 1 and s["compile_s_total"] == 1.5
    assert s["compile_s_by_kind"] == {"unknown": 1.5}
    # slowest is newest-row-per-name: the superseded first lowering of
    # train_step is not a second entry
    assert [e["name"] for e in s["slowest"]] == ["train_step"]
    assert s["slowest"][0]["compile_s"] == 0.5


def test_summarize_ledger_splits_compile_kinds():
    """A dir holding both a warmup baseline ("aot") and a live run
    ("first_step") reports the two compile-second units apart — the
    summary must not melt incompatible units into one figure the way
    diff_ledgers refuses to compare them."""
    rows = [
        {"kind": "exec", "name": "train_step", "compile_s": 32.4,
         "compile_kind": "aot", "fingerprint": "ff"},
        {"kind": "exec", "name": "train_step", "compile_s": 70.6,
         "compile_kind": "first_step", "fingerprint": "ff"},
    ]
    s = summarize_ledger(rows)
    assert s["compile_s_by_kind"] == {"aot": 32.4, "first_step": 70.6}
    assert s["recompiles"] == 0  # same fingerprint, different recorder
    assert len(s["slowest"]) == 1  # one executable, newest row wins
    assert s["slowest"][0]["compile_kind"] == "first_step"


def test_load_ledger_tolerates_torn_trailing_write(tmp_path):
    p = tmp_path / "ledger.jsonl"
    p.write_text(json.dumps({"kind": "exec", "name": "a",
                             "fingerprint": "ff"}) + "\n"
                 + '{"kind": "exec", "name": "b", "finge')
    rows = load_ledger(str(tmp_path))
    assert len(rows) == 1 and rows[0]["name"] == "a"


def test_disabled_ledger_writes_nothing_but_still_counts(tmp_path):
    led = ExecutableLedger(str(tmp_path), enabled=False, backend="cpu")
    led.record("x", compile_s=0.1)
    led.flush()
    assert not (tmp_path / "ledger.jsonl").exists()
    assert led.stats()["exec_lowerings"] == 1


# ------------------------------------------------------- diff verdicts


def test_diff_ledgers_failure_classes_and_reported_only_names():
    base = [{"kind": "exec", "name": "a", "fingerprint": "f1",
             "cache_hits": 1, "cache_misses": 0, "compile_s": 0.5,
             "argument_bytes": 100, "output_bytes": 50, "temp_bytes": 50}]
    same = [dict(base[0])]
    assert diff_ledgers(base, same)["failed"] is False
    # memory growth under the bound does not fail
    near = [dict(base[0], argument_bytes=110)]
    assert diff_ledgers(base, near)["failed"] is False
    # a new or missing name is reported, never fails
    v = diff_ledgers(base, same + [dict(base[0], name="b")])
    assert v["new"] == ["b"] and v["failed"] is False
    v = diff_ledgers(base + [dict(base[0], name="b")], same)
    assert v["missing"] == ["b"] and v["failed"] is False
    # each class alone fails
    assert diff_ledgers(base, [dict(base[0], fingerprint="f2")])[
        "fingerprint_drift"]
    assert diff_ledgers(base, [dict(base[0], cache_hits=0,
                                    cache_misses=1)])[
        "unexpected_recompiles"]
    assert diff_ledgers(base, [dict(base[0], compile_s=1.5)])[
        "compile_blowups"]  # > max(floor 1.0, 0.5 * 2.0)
    # ... but only between rows of the SAME compile_kind: a warmup
    # baseline's pure lower+compile ("aot") never bounds the train
    # loop's first-step wall ("first_step" = compile + one executed
    # step) — mixed units must not fire a false rc 8
    assert diff_ledgers(
        [dict(base[0], compile_kind="aot")],
        [dict(base[0], compile_s=1.5, compile_kind="first_step")])[
        "compile_blowups"] == []
    assert diff_ledgers(
        [dict(base[0], compile_kind="aot")],
        [dict(base[0], compile_s=1.5, compile_kind="aot")])[
        "compile_blowups"]
    assert diff_ledgers(base, [dict(base[0], temp_bytes=200)])[
        "memory_growth"]  # 350 > 200 * 1.2
    # bounds are parameters: a looser memory factor passes the same rows
    assert diff_ledgers(base, [dict(base[0], temp_bytes=200)],
                        memory_factor=2.0)["failed"] is False
    # the compile floor swallows sub-floor blowups (cpu-noise guard)
    tiny = [dict(base[0], compile_s=0.01)]
    assert diff_ledgers(tiny, [dict(base[0], compile_s=0.9)],
                        compile_factor=DEFAULT_COMPILE_FACTOR)[
        "failed"] is False


def test_fixture_verdicts_byte_pinned():
    """The recorded fixture's diff verdicts are byte-for-byte the
    committed goldens — drift classification can never move silently."""
    base = load_ledger(os.path.join(FIXTURE, "baseline.jsonl"))
    for name, want_failed in (("clean", False), ("drift", True)):
        run = load_ledger(os.path.join(FIXTURE, f"run_{name}"))
        got = diff_ledgers(base, run)
        assert got["failed"] is want_failed
        assert json.dumps(got) == json.dumps(
            _golden(f"ledger_diff_{name}.json"))


# ---------------------------------------------------- rc 8 CLI contract


def test_ledger_diff_cli_exit_codes(tmp_path):
    tool = os.path.join(REPO, "tools", "ledger_diff.py")
    base = os.path.join(FIXTURE, "baseline.jsonl")

    def run(*args):
        return subprocess.run([sys.executable, tool, *args], cwd=REPO,
                              capture_output=True, text=True)

    drift = run("--baseline", base, "--run",
                os.path.join(FIXTURE, "run_drift"))
    assert drift.returncode == 8
    verdict = json.loads(drift.stdout)
    assert verdict["failed"] and verdict["fingerprint_drift"]
    clean = run("--baseline", base, "--run",
                os.path.join(FIXTURE, "run_clean"))
    assert clean.returncode == 0
    assert json.loads(clean.stdout)["failed"] is False
    # loosened bounds flip the blowup/growth classes off (drift remains)
    loose = run("--baseline", base, "--run",
                os.path.join(FIXTURE, "run_drift"),
                "--compile-factor", "10", "--memory-factor", "10")
    v = json.loads(loose.stdout)
    assert loose.returncode == 8  # fingerprint drift still fails
    assert not v["compile_blowups"] and not v["memory_growth"]
    missing = run("--baseline", base, "--run", str(tmp_path / "nope"))
    assert missing.returncode == 1


def _run_copy(tmp_path, which: str, with_baseline: bool,
              dest: str | None = None) -> str:
    d = str(tmp_path / (dest or which))
    shutil.copytree(os.path.join(FIXTURE, which), d)
    if with_baseline:
        shutil.copy(os.path.join(FIXTURE, "baseline.jsonl"),
                    os.path.join(d, "ledger_baseline.jsonl"))
    return d


def test_tail_exits_8_on_ledger_drift_and_0_on_clean(tmp_path, capsys):
    from deepof_tpu.cli import main

    drift_dir = _run_copy(tmp_path, "run_drift", with_baseline=True)
    assert main(["tail", "--log-dir", drift_dir]) == 8
    summary = json.loads(capsys.readouterr().out)
    assert summary["ledger_diff"]["failed"] is True
    assert summary["ledger"]["lowerings"] == 5
    clean_dir = _run_copy(tmp_path, "run_clean", with_baseline=True)
    assert main(["tail", "--log-dir", clean_dir]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["ledger_diff"]["failed"] is False
    assert summary["ledger_diff"]["fingerprint_drift"] == []
    assert summary["ledger_diff"]["unexpected_recompiles"] == []
    # no baseline => no verdict, never a failure
    bare_dir = _run_copy(tmp_path, "run_clean", with_baseline=False,
                         dest="run_bare")
    assert main(["tail", "--log-dir", bare_dir]) == 0
    assert "ledger_diff" not in json.loads(capsys.readouterr().out)
    # an explicit --ledger-baseline needs no copied convention file
    assert main(["tail", "--log-dir", bare_dir, "--ledger-baseline",
                 os.path.join(FIXTURE, "baseline.jsonl")]) == 0
    capsys.readouterr()
    # ... and a run DIR holding a ledger.jsonl is a valid baseline too,
    # exactly as the standalone ledger_diff accepts it (the two gates
    # must agree on valid inputs, not just on bad ones)
    assert main(["tail", "--log-dir", bare_dir, "--ledger-baseline",
                 os.path.join(FIXTURE, "run_clean")]) == 0
    capsys.readouterr()
    # loosened tail bounds mirror ledger_diff's flags
    assert main(["tail", "--log-dir", drift_dir,
                 "--ledger-compile-factor", "10",
                 "--ledger-memory-factor", "10"]) == 8  # drift remains
    capsys.readouterr()
    # an empty/truncated baseline is STATIC — it can never become
    # valid, so the pre-check fails it loudly even before any summary
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    with pytest.raises(SystemExit, match="no ledger rows"):
        main(["tail", "--log-dir", drift_dir,
              "--ledger-baseline", str(empty)])
    with pytest.raises(SystemExit, match="no ledger rows"):
        main(["tail", "--log-dir", drift_dir, "--follow",
              "--ledger-baseline", str(empty)])
    # ...and the committed-by-convention file gets the same treatment:
    # an EXISTING but rowless <log_dir>/ledger_baseline.jsonl is a
    # broken gate, not the legitimate no-baseline case
    conv_dir = _run_copy(tmp_path, "run_clean", with_baseline=False,
                         dest="run_conv")
    open(os.path.join(conv_dir, "ledger_baseline.jsonl"), "w").close()
    with pytest.raises(SystemExit, match="no ledger rows"):
        main(["tail", "--log-dir", conv_dir])


def test_tail_follow_waits_for_first_ledger_row(tmp_path):
    """`tail --follow --ledger-baseline B` on a run that has not yet
    written its first ledger row (first compile pending — can be
    minutes cold) keeps following instead of dying rc 1 on iteration
    one; once rows appear the gate fires like every other rc 3-8
    condition. A one-shot (no --follow) tail on the same inputs stays
    a loud rc-1 error."""
    import time as _time

    run = tmp_path / "run"
    run.mkdir()
    (run / "metrics.jsonl").write_text(json.dumps(
        {"kind": "train", "step": 1, "time": 0.0, "total": 0.5}) + "\n")
    base = os.path.join(FIXTURE, "baseline.jsonl")
    from deepof_tpu.cli import main

    with pytest.raises(SystemExit, match="no verdict"):
        main(["tail", "--log-dir", str(run), "--ledger-baseline", base])
    proc = subprocess.Popen(
        [sys.executable, "-m", "deepof_tpu", "tail", "--log-dir",
         str(run), "--follow", "--interval", "0.2",
         "--ledger-baseline", base],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True)
    try:
        _time.sleep(2.0)
        assert proc.poll() is None, proc.stderr.read()
        # the run's first rows land — drifted vs the baseline => rc 8
        shutil.copy(os.path.join(FIXTURE, "run_drift", "ledger.jsonl"),
                    run / "ledger.jsonl")
        assert proc.wait(timeout=30) == 8
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def test_fleet_ledger_drift_keeps_full_schema_without_root_ledger(
        tmp_path):
    """tail --fleet's drift verdict carries the full documented
    diff_ledgers schema even when only CHILDREN recorded ledgers (a
    router that lowered nothing above replica processes): the verdict's
    shape must not depend on whether the root happened to have one."""
    from deepof_tpu.analyze import ledger_drift
    from deepof_tpu.obs.ledger import diff_ledgers

    shutil.copy(os.path.join(FIXTURE, "baseline.jsonl"),
                tmp_path / "ledger_baseline.jsonl")
    child = tmp_path / "replica-0"
    child.mkdir()
    (child / "metrics.jsonl").write_text(json.dumps(
        {"kind": "train", "step": 1, "time": 0.0}) + "\n")
    shutil.copy(os.path.join(FIXTURE, "run_drift", "ledger.jsonl"),
                child / "ledger.jsonl")
    v = ledger_drift(str(tmp_path), fleet=True)
    reference = diff_ledgers([], [])
    assert set(reference) | {"children"} == set(v)
    assert v["failed"] is True  # the drifted child fails the fleet
    assert v["children"]["replica-0"]["failed"] is True
    assert v["fingerprint_drift"] == []  # root compared nothing


def test_ledger_verdict_none_when_either_side_absent(tmp_path):
    assert ledger_verdict(str(tmp_path)) is None  # no baseline
    shutil.copy(os.path.join(FIXTURE, "baseline.jsonl"),
                os.path.join(tmp_path, "ledger_baseline.jsonl"))
    assert ledger_verdict(str(tmp_path)) is None  # no run ledger


# ------------------------------------------------ engine path (ledger)


def test_engine_records_serve_executable_and_exec_stats(tmp_path):
    """The real engine path (jit -> AOT compile over the tiny
    elementwise model, test_serve lineage): one ledger row per lattice
    compile, measured-dispatch timings flushed at close, and the
    registry-declared exec_* block in stats() — while obs.ledger=false
    keeps the stats schema byte-identical to the pre-ledger stack and
    writes nothing."""
    from test_serve import _cfg, _img, _tiny_model_params

    rng = np.random.RandomState(0)
    cfg = _cfg(max_batch=2, timeout_ms=5.0, log_dir=str(tmp_path))
    from deepof_tpu.serve.engine import InferenceEngine

    with InferenceEngine(cfg, model_params=_tiny_model_params()) as eng:
        futs = [eng.submit(_img(rng), _img(rng)) for _ in range(4)]
        for f in futs:
            f.result(timeout=60)
        stats = eng.stats()
    assert stats["exec_lowerings"] >= 1
    assert stats["exec_recompiles"] == 0
    name = exec_name((32, 64), "f32", "cold")
    assert name in stats["exec_fingerprints"]
    assert stats["exec_dispatches"] >= 1
    rows = load_ledger(str(tmp_path))
    execs = [r for r in rows if r["kind"] == "exec"]
    timings = [r for r in rows if r["kind"] == "exec_timing"]
    assert [r["name"] for r in execs] == [name]
    assert execs[0]["fingerprint"] == stats["exec_fingerprints"][name]
    assert execs[0]["compile_s"] > 0
    assert execs[0]["compile_kind"] == "aot"  # record_aot stamps it
    assert timings and timings[0]["name"] == name
    assert timings[0]["count"] == stats["exec_dispatches"]

    # ledger off: schema byte-identical to the pre-ledger stack
    off_dir = tmp_path / "off"
    off_cfg = _cfg(max_batch=2, timeout_ms=5.0, log_dir=str(off_dir))
    off_cfg = off_cfg.replace(obs=dataclasses.replace(off_cfg.obs,
                                                      ledger=False))
    with InferenceEngine(off_cfg,
                         model_params=_tiny_model_params()) as eng:
        eng.submit(_img(rng), _img(rng)).result(timeout=60)
        off_stats = eng.stats()
    assert not any(k.startswith("exec_") for k in off_stats)
    assert not os.path.exists(os.path.join(str(off_dir), "ledger.jsonl"))
    assert (sorted(k for k in stats if not k.startswith("exec_"))
            == sorted(off_stats))


def test_ledger_preresolve_compile_failure_contained(tmp_path):
    """A compile error inside the ledger's pre-resolution (the
    executable is resolved BEFORE the timed window so the first
    measured dispatch is an execution, not compile+execution) fails
    that flush's futures as structured dispatch_failed errors — it must
    never kill the batcher thread and strand the futures forever."""
    from test_serve import _cfg, _img, _tiny_model_params

    from deepof_tpu.serve.engine import InferenceEngine, ServeError

    rng = np.random.RandomState(0)
    cfg = _cfg(max_batch=2, timeout_ms=5.0, log_dir=str(tmp_path))
    with InferenceEngine(cfg, model_params=_tiny_model_params()) as eng:
        assert eng._ledger is not None  # the path under test is active

        def boom(key):
            raise RuntimeError("injected compile failure")

        eng._executable = boom
        futs = [eng.submit(_img(rng), _img(rng)) for _ in range(3)]
        for f in futs:
            with pytest.raises(ServeError) as exc:
                f.result(timeout=30)
            assert exc.value.code == "dispatch_failed"
        stats = eng.stats()  # the batcher survived to serve stats
    assert stats["serve_errors"] == 3
    # the pre-resolve failure counts as a dispatch failure exactly like
    # the _forward path — serve_dispatch_failures must not undercount
    # compile failures just because the ledger pre-resolve caught them
    assert stats["serve_dispatch_failures"] >= 1


# -------------------------------------------- telemetry direct coverage


def test_step_flops_and_lowered_flops_agree_on_a_matmul():
    import jax
    import jax.numpy as jnp

    from deepof_tpu.obs.telemetry import lowered_flops, step_flops

    f = jax.jit(lambda x: x @ x)
    x = jnp.ones((16, 16), jnp.float32)
    direct = step_flops(f, x)
    assert direct is not None and direct > 0
    assert direct == lowered_flops(f.lower(x))
    # a 16x16 matmul is ~2*16^3 flops; the estimate must be that order
    assert 16 ** 3 <= direct <= 4 * 16 ** 3
    # best-effort contract: garbage in => None, never a raise
    assert lowered_flops(object()) is None
    assert step_flops(object()) is None


def test_device_memory_summary_schema_stable_on_any_backend():
    from deepof_tpu.obs.telemetry import (device_memory_stats,
                                          device_memory_summary)

    stats = device_memory_stats()
    assert stats and all(set(s) == {"device", "bytes_in_use",
                                    "peak_bytes_in_use"} for s in stats)
    summary = device_memory_summary()
    # keys always present; None where the backend (cpu PJRT) is silent
    assert set(summary) == {"dev_mem_bytes_in_use", "dev_mem_peak_bytes"}
    for v in summary.values():
        assert v is None or (isinstance(v, int) and v >= 0)


def test_process_rss_bytes_reports_linux_rss():
    from deepof_tpu.obs.telemetry import process_rss_bytes

    rss = process_rss_bytes()
    assert rss is None or rss > 1024 * 1024  # a live python is > 1 MB


# -------------------------------------------------------- trend schema


def test_bench_trend_ledger_series_and_trend_flag(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_trend", os.path.join(REPO, "tools", "bench_trend.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    # four rounds: overhead creeping up (a sustained slide past the
    # tolerance => the trend block flags), per-executable compile
    # seconds stable
    for rnd, pct, q_scorer, q_p99 in ((1, 1.0, -0.5, 1.0),
                                      (2, 1.4, 0.2, 2.0),
                                      (3, 2.0, 0.4, 4.0),
                                      (4, 3.0, 0.6, 6.0)):
        (tmp_path / f"BENCH_r{rnd:02d}.json").write_text(json.dumps({
            "ledger": {
                "p99_overhead_pct": pct,
                "compile_s_total": 0.9,
                "mfu_nominal": 2e-05,
                "executables": {
                    "serve:32x64:f32:cold": {"compile_s": 0.9,
                                             "mfu_nominal": 2e-05}}},
            "serve_bench_quality": {"scorer_overhead_pct": q_scorer,
                                    "p99_overhead_pct": q_p99}}))
    report = mod.bench_trend(str(tmp_path), tolerance=0.3)
    assert "trend" in report  # REQUIRED_KEYS gained the block
    over = report["series"]["bench_ledger_overhead_pct"]
    assert [p["value"] for p in over] == [1.0, 1.4, 2.0, 3.0]
    t = report["trend"]["bench_ledger_overhead_pct"]
    assert t["slope_per_round"] > 0 and t["regressing"] is True
    # dynamic per-executable series materialized with per-point sense
    key = "ledger_compile_s:serve:32x64:f32:cold"
    assert [p["value"] for p in report["series"][key]] == [0.9] * 4
    assert report["trend"][key]["regressing"] is False
    # stable series never flag
    assert not report["trend"]["bench_ledger_compile_s"]["regressing"]
    # the quality P99 overhead carries ISSUE 13's 5% acceptance bound:
    # 6.0 > 5.0 in the newest round flags it...
    assert "bench_quality_p99_overhead_pct" in report["regressions"]
    assert report["trend"]["bench_quality_p99_overhead_pct"][
        "regressing"] is True
    # ...while the rps-based scorer companion is noise-centered with NO
    # absolute acceptance: a -0.5 best vs +0.6 latest (relative-to-best
    # meaningless) must never auto-flag
    assert "bench_quality_scorer_overhead_pct" not in report["regressions"]
    assert report["trend"]["bench_quality_scorer_overhead_pct"][
        "regressing"] is False

    # compile-seconds series are cache-BIMODAL: a cache-hit round's
    # 0.05 s best must not turn a healthy cold round (0.86 s) into a
    # 17x phantom blowup — the ledger's own max(floor 1s, best*2) rule
    # applies; a genuine blowup past the floor still flags
    bimodal = tmp_path / "bimodal"
    bimodal.mkdir()
    for rnd, cs, mfu in ((1, 0.05, 3.8e-05), (2, 0.9, 3.0e-05),
                         (3, 0.06, 2.4e-05), (4, 0.86, 1.9e-05)):
        (bimodal / f"BENCH_r{rnd:02d}.json").write_text(json.dumps({
            "ledger": {"compile_s_total": cs, "mfu_nominal": mfu,
                       "executables": {
                           "serve:32x64:f32:cold": {
                               "compile_s": cs, "mfu_nominal": mfu}}}}))
    rep = mod.bench_trend(str(bimodal), tolerance=0.3)
    assert "bench_ledger_compile_s" not in rep["regressions"]
    assert rep["trend"]["bench_ledger_compile_s"]["regressing"] is False
    assert f"ledger_compile_s:serve:32x64:f32:cold" not in rep[
        "regressions"]
    # measured MFU halves on a contended host (wall-derived noise):
    # recorded and sloped, never auto-flagged
    assert "bench_ledger_mfu" not in rep["regressions"]
    assert rep["trend"]["bench_ledger_mfu"]["regressing"] is False
    assert "ledger_mfu_nominal:serve:32x64:f32:cold" not in rep[
        "regressions"]
    # the compile bound compares against the WORST prior round, so a
    # repeated healthy cold compile ABOVE the 1 s floor (32 s, 31 s)
    # never phantom-flags against a cache-hit best of 0.05 s
    big = tmp_path / "bigcold"
    big.mkdir()
    for rnd, cs in ((1, 0.05), (2, 32.0), (3, 0.06), (4, 31.0)):
        (big / f"BENCH_r{rnd:02d}.json").write_text(json.dumps({
            "ledger": {"compile_s_total": cs, "executables": {
                "serve:32x64:f32:cold": {"compile_s": cs}}}}))
    rep = mod.bench_trend(str(big), tolerance=0.3)
    assert "bench_ledger_compile_s" not in rep["regressions"]
    assert "ledger_compile_s:serve:32x64:f32:cold" not in rep[
        "regressions"]
    blow = tmp_path / "blow"
    blow.mkdir()
    for rnd, cs in ((1, 0.05), (2, 0.06), (3, 2.5)):
        (blow / f"BENCH_r{rnd:02d}.json").write_text(json.dumps({
            "ledger": {"compile_s_total": cs, "executables": {
                "serve:32x64:f32:cold": {"compile_s": cs}}}}))
    rep = mod.bench_trend(str(blow), tolerance=0.3)
    assert "bench_ledger_compile_s" in rep["regressions"]
    assert rep["regressions"]["bench_ledger_compile_s"][
        "compile_floor_s"] == 1.0
    assert rep["trend"]["bench_ledger_compile_s"]["regressing"] is True
    assert "ledger_compile_s:serve:32x64:f32:cold" in rep["regressions"]


def test_serve_bench_ledger_required_keys_schema():
    """serve_bench --ledger over the real (tiny-width) model: the
    LEDGER_REQUIRED_KEYS schema holds and the provenance block is
    self-consistent. The overhead FIGURE is recorded by BENCH runs, not
    asserted here — a loaded CI host makes p99 deltas meaningless."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(REPO, "tools", "serve_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    res = mod.ledger_bench(requests=6, gap_ms=0.0, max_batch=2,
                           timeout_ms=5.0, bucket=(32, 64),
                           native_hw=(30, 60))
    for key in mod.LEDGER_REQUIRED_KEYS:
        assert key in res, key
    assert res["lowerings"] >= 1 and res["recompiles"] == 0
    name = exec_name((32, 64), "f32", "cold")
    assert name in res["executables"]
    assert res["executables"][name]["fingerprint"]
    assert res["compile_s_total"] > 0
    assert res["p99_ledger_on_ms"] > 0 and res["p99_ledger_off_ms"] > 0
