"""Log-analysis tool (the reference's `analyze_test_loss.py` counterpart)."""

import json

from deepof_tpu.analyze import analyze, load_records, summarize


def _write_log(tmp_path, records):
    with open(tmp_path / "metrics.jsonl", "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
        f.write('{"torn\n')  # torn write from a killed run must be tolerated


def test_summarize_and_load(tmp_path):
    _write_log(tmp_path, [
        {"kind": "info", "step": 0, "message": "params: 1"},
        {"kind": "train", "step": 100, "loss": 5.0, "lr": 1e-4,
         "items_per_sec_per_chip": 10.0},
        {"kind": "train", "step": 200, "loss": 3.0, "lr": 1e-4},
        {"kind": "eval", "step": 200, "aee": 4.5, "aae": 1.2},
        {"kind": "eval", "step": 400, "aee": 3.5, "aae": 1.0},
        {"kind": "train", "step": 400, "loss": 4.0, "lr": 5e-5},
        {"kind": "warn", "step": 401, "message": "NaN; rolled back"},
    ])
    recs = load_records(str(tmp_path))
    assert len(recs) == 7  # torn line dropped
    s = summarize(recs)
    assert s["train"]["best_loss"] == 3.0 and s["train"]["best_step"] == 200
    assert s["train"]["last_loss"] == 4.0
    assert s["eval"]["best_aee"] == 3.5 and s["eval"]["evals"] == 2
    assert s["warnings"] == ["NaN; rolled back"]

    out = analyze(str(tmp_path), plot=True)
    assert out["counts"]["train"] == 3
    # plots written only if matplotlib exists; either way the key is present
    assert isinstance(out.get("plots", []), list)


def test_accuracy_summary(tmp_path):
    _write_log(tmp_path, [
        {"kind": "eval", "step": 10, "accuracy": 0.4},
        {"kind": "eval", "step": 20, "accuracy": 0.6},
    ])
    s = summarize(load_records(str(tmp_path)))
    assert s["accuracy"]["best"] == 0.6


def test_nan_records_excluded(tmp_path):
    _write_log(tmp_path, [
        {"kind": "train", "step": 1, "loss": float("nan")},
        {"kind": "train", "step": 2, "loss": 2.5},
    ])
    s = summarize(load_records(str(tmp_path)))
    assert s["train"]["best_loss"] == 2.5  # NaN must not win min()
    assert s["non_finite_train_records"] == 1
    # the summary must stay strict-JSON serializable
    json.dumps(s, allow_nan=False)


def test_analyze_is_jax_free():
    """The tool must be usable next to a live trainer: importing it cannot
    initialize an accelerator backend."""
    import subprocess
    import sys

    code = ("import sys; import deepof_tpu.analyze; "
            "sys.exit(1 if 'jax' in sys.modules else 0)")
    res = subprocess.run([sys.executable, "-c", code], timeout=60,
                         env={"PATH": "/usr/bin:/bin", "PYTHONPATH": "/root/repo"},
                         capture_output=True)
    assert res.returncode == 0, res.stderr.decode()[-500:]
