"""Log-analysis tool (the reference's `analyze_test_loss.py` counterpart)."""

import json

from deepof_tpu.analyze import analyze, load_records, summarize


def _write_log(tmp_path, records):
    with open(tmp_path / "metrics.jsonl", "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
        f.write('{"torn\n')  # torn write from a killed run must be tolerated


def test_summarize_and_load(tmp_path):
    _write_log(tmp_path, [
        {"kind": "info", "step": 0, "message": "params: 1"},
        {"kind": "train", "step": 100, "loss": 5.0, "lr": 1e-4,
         "items_per_sec_per_chip": 10.0},
        {"kind": "train", "step": 200, "loss": 3.0, "lr": 1e-4},
        {"kind": "eval", "step": 200, "aee": 4.5, "aae": 1.2},
        {"kind": "eval", "step": 400, "aee": 3.5, "aae": 1.0},
        {"kind": "train", "step": 400, "loss": 4.0, "lr": 5e-5},
        {"kind": "warn", "step": 401, "message": "NaN; rolled back"},
    ])
    recs = load_records(str(tmp_path))
    assert len(recs) == 7  # torn line dropped
    s = summarize(recs)
    assert s["train"]["best_loss"] == 3.0 and s["train"]["best_step"] == 200
    assert s["train"]["last_loss"] == 4.0
    assert s["eval"]["best_aee"] == 3.5 and s["eval"]["evals"] == 2
    assert s["warnings"] == ["NaN; rolled back"]

    out = analyze(str(tmp_path), plot=True)
    assert out["counts"]["train"] == 3
    # plots written only if matplotlib exists; either way the key is present
    assert isinstance(out.get("plots", []), list)


def test_accuracy_summary(tmp_path):
    _write_log(tmp_path, [
        {"kind": "eval", "step": 10, "accuracy": 0.4},
        {"kind": "eval", "step": 20, "accuracy": 0.6},
    ])
    s = summarize(load_records(str(tmp_path)))
    assert s["accuracy"]["best"] == 0.6


def test_nan_records_excluded(tmp_path):
    _write_log(tmp_path, [
        {"kind": "train", "step": 1, "loss": float("nan")},
        {"kind": "train", "step": 2, "loss": 2.5},
    ])
    s = summarize(load_records(str(tmp_path)))
    assert s["train"]["best_loss"] == 2.5  # NaN must not win min()
    assert s["non_finite_train_records"] == 1
    # the summary must stay strict-JSON serializable
    json.dumps(s, allow_nan=False)


def test_phase_and_counter_aggregation(tmp_path):
    """summarize() folds the cumulative phase_*_s / starved / data_*
    fields of the freshest train record into shares and rates."""
    _write_log(tmp_path, [
        {"kind": "train", "step": 100, "loss": 5.0,
         "phase_assemble_s": 1.0, "phase_dispatch_s": 2.0,
         "phase_fetch_s": 1.0, "starved": 2, "data_queue_depth": 1},
        {"kind": "train", "step": 200, "loss": 3.0,
         "phase_assemble_s": 2.0, "phase_dispatch_s": 5.0,
         "phase_fetch_s": 3.0, "starved": 10, "data_queue_depth": 2,
         "data_worker_util": 0.8},
    ])
    s = summarize(load_records(str(tmp_path)))
    assert s["phases"]["seconds"] == {"assemble": 2.0, "dispatch": 5.0,
                                      "fetch": 3.0}
    share = s["phases"]["share"]
    assert share["dispatch"] == 0.5
    assert abs(sum(share.values()) - 1.0) < 1e-6
    assert s["counters"]["starved"] == 10
    assert s["counters"]["starvation_rate"] == 0.05  # 10 / 200 steps
    assert s["counters"]["data"]["worker_util"] == 0.8
    json.dumps(s, allow_nan=False)  # summary stays strict-JSON


def test_tail_summary(tmp_path):
    from deepof_tpu.analyze import tail_summary

    now = 1000.0
    _write_log(tmp_path, [
        {"kind": "train", "step": 100, "time": now - 30, "loss": 5.0,
         "steps_per_sec": 10.0, "items_per_sec_per_chip": 40.0},
        {"kind": "train", "step": 200, "time": now - 20, "loss": 4.0,
         "steps_per_sec": 10.0, "items_per_sec_per_chip": 40.0},
        {"kind": "train", "step": 300, "time": now - 10, "loss": 3.0,
         "steps_per_sec": 10.0, "items_per_sec_per_chip": 40.0,
         "phase_dispatch_s": 3.0, "phase_assemble_s": 1.0, "starved": 3,
         "model_tflops": 1.5, "rss_bytes": 123},
        {"kind": "eval", "step": 300, "time": now - 9, "aee": 2.5},
        {"kind": "warn", "step": 301, "time": now - 8, "message": "x"},
    ])
    with open(tmp_path / "heartbeat.json", "w") as f:
        json.dump({"time": now - 4, "step": 300, "wedged": False,
                   "wedges": 0, "last_step_age_s": 1.2,
                   "heartbeat_period_s": 5.0}, f)
    s = tail_summary(str(tmp_path), recent=3, now=now)
    assert s["step"] == 300 and s["loss"] == 3.0
    # slope over the recent window: 200 steps / 20 s
    assert s["recent_steps_per_sec"] == 10.0
    assert s["throughput_trend"] == 1.0
    assert s["phase_share"] == {"assemble": 0.25, "dispatch": 0.75}
    assert s["starved"] == 3 and s["starvation_rate"] == 0.01
    assert s["model_tflops"] == 1.5 and s["rss_bytes"] == 123
    assert s["last_eval"] == {"step": 300, "aee": 2.5}
    assert s["warnings"] == 1 and s["last_warning"] == "x"
    hb = s["heartbeat"]
    assert hb["age_s"] == 4.0 and hb["wedged"] is False
    assert hb["step"] == 300
    json.dumps(s, allow_nan=False)


def test_tail_summary_without_heartbeat(tmp_path):
    from deepof_tpu.analyze import tail_summary

    _write_log(tmp_path, [
        {"kind": "train", "step": 10, "time": 5.0, "loss": 1.0},
    ])
    s = tail_summary(str(tmp_path), now=10.0)
    assert s["step"] == 10
    assert "heartbeat" not in s
    assert s["last_record_age_s"] == 5.0


def test_analyze_is_jax_free():
    """The tool must be usable next to a live trainer: importing it cannot
    initialize an accelerator backend."""
    import subprocess
    import sys

    code = ("import sys; import deepof_tpu.analyze; "
            "sys.exit(1 if 'jax' in sys.modules else 0)")
    res = subprocess.run([sys.executable, "-c", code], timeout=60,
                         env={"PATH": "/usr/bin:/bin", "PYTHONPATH": "/root/repo"},
                         capture_output=True)
    assert res.returncode == 0, res.stderr.decode()[-500:]
