"""Serving-fleet tests (DESIGN.md "Fleet").

Unit tier (in-process, no subprocess): the parent->replica config.json
round-trip, the router's header-only image-dimension probe, and the
routing policies — bucket affinity, load spill, saturation shedding,
failover replay — against stub replica HTTP servers.

Chaos tier (subprocess replicas, fake timed executor — jax-free, a few
seconds of startup each): the ISSUE 6 acceptance — under sustained load
with one injected replica SIGKILL (`replica_crash`) and one injected
wedge (`replica_wedge`), >= 99% of requests succeed via failover, the
sick replicas are evicted and respawned, zero requests are silently
dropped, and `deepof_tpu tail` exits 4 surfacing the evictions; plus the
crash-loop circuit breaker and the `serve_bench --fleet` >= 1.5x
two-replica throughput acceptance.
"""

import base64
import dataclasses
import http.client
import importlib.util
import json
import os
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

from conftest import free_port, wait_for_listen

from deepof_tpu.core.config import config_from_dict, get_config
from deepof_tpu.serve.fleet import Fleet
from deepof_tpu.serve.router import (Router, build_router_server,
                                     probe_image_hw)

# ----------------------------------------------------------- helpers


def _fleet_cfg(log_dir, max_batch=4, timeout_ms=10.0, exec_ms=5.0,
               buckets=(), image_size=(32, 64), **fleet_kw):
    """Fast-cadence fleet config for tests: sub-second health polling,
    short grace/backoff windows, fake timed executor replicas."""
    fleet_defaults = dict(poll_s=0.1, stale_after_s=5.0, stall_after_s=2.0,
                          spawn_timeout_s=90.0,
                          term_grace_s=1.0, backoff_s=0.1, backoff_max_s=0.5,
                          healthy_after_s=30.0, proxy_timeout_s=2.0,
                          max_in_flight=64, drain_timeout_s=2.0)
    fleet_defaults.update(fleet_kw)
    cfg = get_config("flyingchairs")
    return cfg.replace(
        model="flownet_s", width_mult=0.25,
        data=dataclasses.replace(cfg.data, dataset="synthetic",
                                 image_size=image_size, gt_size=image_size),
        serve=dataclasses.replace(
            cfg.serve, max_batch=max_batch, batch_timeout_ms=timeout_ms,
            buckets=buckets, fake_exec_ms=exec_ms, host="127.0.0.1", port=0,
            fleet=dataclasses.replace(cfg.serve.fleet, **fleet_defaults)),
        train=dataclasses.replace(cfg.train, eval_amplifier=1.0,
                                  eval_clip=(-1e6, 1e6),
                                  log_dir=str(log_dir)),
        obs=dataclasses.replace(cfg.obs, heartbeat_period_s=0.1,
                                watchdog_min_s=0.5))


def _b64png(rng, hw=(30, 60)):
    ok, buf = cv2.imencode(
        ".png", rng.randint(1, 255, (*hw, 3), dtype=np.uint8))
    assert ok
    return base64.b64encode(buf.tobytes()).decode()


def _flow_body(rng, hw=(30, 60)) -> bytes:
    return json.dumps({"prev": _b64png(rng, hw),
                       "next": _b64png(rng, hw)}).encode()


def _post(port, body, path="/v1/flow", timeout=30.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body, {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _get_json(port, path="/healthz", timeout=10.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _start_router(cfg, fleet):
    router = Router(cfg, fleet)
    httpd = build_router_server(cfg, router)
    threading.Thread(target=httpd.serve_forever, daemon=True,
                     name="test-router").start()
    port = httpd.server_address[1]
    wait_for_listen("127.0.0.1", port)
    return router, httpd, port


# -------------------------------------------------- config round-trip


def test_config_json_round_trip():
    """The parent->replica handoff: asdict -> JSON -> config_from_dict
    reproduces the exact frozen config tree, nested tuples included."""
    cfg = get_config("flyingchairs")
    cfg = cfg.replace(
        serve=dataclasses.replace(
            cfg.serve, buckets=((64, 64), (32, 64)), fake_exec_ms=3.0,
            fleet=dataclasses.replace(cfg.serve.fleet, replicas=3,
                                      backoff_s=0.25)),
        resilience=dataclasses.replace(
            cfg.resilience,
            faults=dataclasses.replace(cfg.resilience.faults, enabled=True,
                                       replica_crash_at=(0, 2),
                                       decode_at=(1, 5))))
    restored = config_from_dict(json.loads(json.dumps(
        dataclasses.asdict(cfg))))
    assert restored == cfg
    assert restored.serve.buckets == ((64, 64), (32, 64))
    assert restored.resilience.faults.replica_crash_at == (0, 2)
    # typo rejection (typos at ANY level must not silently become
    # defaults) moved to the registry-driven whole-tree walk in
    # test_lint.py, which keeps this file's original assertions as
    # parity pins


# ------------------------------------------------------- header probe


def test_probe_image_hw_headers_only(rng):
    img = rng.randint(0, 255, (48, 96, 3), dtype=np.uint8)
    for ext in (".png", ".jpg", ".bmp"):
        ok, buf = cv2.imencode(ext, img)
        assert ok
        assert probe_image_hw(buf.tobytes()) == (48, 96), ext
    assert probe_image_hw(b"not an image") is None
    assert probe_image_hw(b"") is None
    # a truncated PNG header (first KB) still probes — the router only
    # ever sees a prefix of the payload
    ok, buf = cv2.imencode(".png", img)
    assert probe_image_hw(buf.tobytes()[:64]) == (48, 96)


# ------------------------------------------- router policy (stub fleet)


class _StubFleet:
    """Duck-typed Fleet for router unit tests: fixed (idx, port) slots,
    None = not ready."""

    def __init__(self, ports, host="127.0.0.1"):
        self.host = host
        self.ports = list(ports)
        self.size = len(self.ports)
        self.failures = []

    def ready_replicas(self):
        return [SimpleNamespace(idx=i, port=p)
                for i, p in enumerate(self.ports) if p is not None]

    def note_failure(self, idx):
        self.failures.append(idx)

    def stats(self):
        return {"fleet_replicas": self.size,
                "fleet_ready": len(self.ready_replicas())}

    def describe(self):
        return []


def _stub_replica(delay_s=0.0):
    """Minimal replica-shaped HTTP server: POST -> optional sleep ->
    200 with its own tag (so tests see who served)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def do_POST(self):  # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            if delay_s:
                time.sleep(delay_s)
            body = json.dumps({"served_by": self.server.server_address[1]})
            body = body.encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def test_router_affinity_maps_buckets_to_replicas(rng, tmp_path):
    """Bucket i of the ladder routes to replica i % N while replicas are
    idle — each replica's AOT executables stay hot for its slice."""
    cfg = _fleet_cfg(tmp_path, buckets=((32, 64), (64, 64)))
    s0, s1 = _stub_replica(), _stub_replica()
    try:
        fleet = _StubFleet([s0.server_address[1], s1.server_address[1]])
        router = Router(cfg, fleet)
        for _ in range(3):  # bucket (32,64) -> ladder[0] -> replica 0
            status, payload, _ = router.handle_flow(
                "/v1/flow", _flow_body(rng, (30, 60)), "application/json")
            assert status == 200
            assert json.loads(payload)["served_by"] == s0.server_address[1]
        for _ in range(3):  # bucket (64,64) -> ladder[1] -> replica 1
            status, payload, _ = router.handle_flow(
                "/v1/flow", _flow_body(rng, (60, 60)), "application/json")
            assert status == 200
            assert json.loads(payload)["served_by"] == s1.server_address[1]
        stats = router.stats()
        assert stats["fleet_routed"] == {"replica-0": 3, "replica-1": 3}
        assert stats["fleet_failovers"] == 0
    finally:
        for s in (s0, s1):
            s.shutdown()
            s.server_close()


def test_router_failover_replays_on_healthy_sibling(rng, tmp_path):
    """A dead replica (connection refused) is retried on the next
    healthy one; the supervisor is poked; exhausting every candidate
    yields a structured 502, never silence."""
    cfg = _fleet_cfg(tmp_path)
    live = _stub_replica()
    try:
        dead_port = free_port()
        fleet = _StubFleet([dead_port, live.server_address[1]])
        router = Router(cfg, fleet)
        status, payload, _ = router.handle_flow(
            "/v1/flow", _flow_body(rng), "application/json")
        assert status == 200
        assert json.loads(payload)["served_by"] == live.server_address[1]
        stats = router.stats()
        assert stats["fleet_retries"] == 1
        assert stats["fleet_failovers"] == 1
        assert fleet.failures == [0]

        # every replica dead -> structured 502 after bounded retries
        fleet2 = _StubFleet([free_port(), free_port()])
        router2 = Router(cfg, fleet2)
        status, payload, _ = router2.handle_flow(
            "/v1/flow", _flow_body(rng), "application/json")
        assert status == 502
        err = json.loads(payload)
        assert err["error"] == "replica_failed"
        assert err["attempts"] == 2

        # no ready replica at all -> structured 503 unavailable
        router3 = Router(cfg, _StubFleet([None, None]))
        status, payload, _ = router3.handle_flow(
            "/v1/flow", _flow_body(rng), "application/json")
        assert status == 503
        assert json.loads(payload)["error"] == "unavailable"
    finally:
        live.shutdown()
        live.server_close()


def test_router_sheds_structured_503_when_saturated(rng, tmp_path):
    """Backpressure at the front: when every healthy replica is at
    fleet.max_in_flight the router answers a structured 503
    ('overloaded') instead of queuing unboundedly; spill past the
    affinity replica happens first."""
    cfg = _fleet_cfg(tmp_path, max_in_flight=1, spill_in_flight=1)
    slow0, slow1 = _stub_replica(delay_s=0.8), _stub_replica(delay_s=0.8)
    try:
        fleet = _StubFleet([slow0.server_address[1],
                            slow1.server_address[1]])
        router = Router(cfg, fleet)
        body = _flow_body(rng)
        results = [None, None, None]

        def call(i):
            results[i] = router.handle_flow("/v1/flow", body,
                                            "application/json")

        threads = []
        for i in range(3):  # 2 saturate both replicas; the 3rd sheds
            t = threading.Thread(target=call, args=(i,))
            t.start()
            threads.append(t)
            time.sleep(0.2)
        for t in threads:
            t.join(timeout=30)
        statuses = sorted(r[0] for r in results)
        assert statuses == [200, 200, 503]
        shed = next(r for r in results if r[0] == 503)
        assert json.loads(shed[1])["error"] == "overloaded"
        stats = router.stats()
        assert stats["fleet_shed"] == 1
        # the two successes spilled across BOTH replicas
        assert set(stats["fleet_routed"]) == {"replica-0", "replica-1"}
    finally:
        for s in (slow0, slow1):
            s.shutdown()
            s.server_close()


# --------------------------------------------------- tail integration


def test_tail_exits_4_surfacing_fleet_evictions(tmp_path, capsys):
    """`tail` must fail scripted health checks when the fleet block
    shows self-healing activity (evictions) or a broken replica — rc 4,
    distinct from the wedged rc 3."""
    from deepof_tpu.cli import main

    (tmp_path / "metrics.jsonl").write_text(json.dumps(
        {"kind": "serve", "step": 0, "time": time.time(),
         "fleet_replicas": 3, "fleet_ready": 3, "fleet_evictions": 0,
         "fleet_broken": 0, "fleet_requests": 10}) + "\n")
    (tmp_path / "heartbeat.json").write_text(json.dumps(
        {"time": time.time(), "step": 10, "wedged": False,
         "fleet_replicas": 3, "fleet_ready": 3, "fleet_evictions": 0,
         "fleet_broken": 0, "fleet_requests": 10, "fleet_failovers": 0}))
    assert main(["tail", "--log-dir", str(tmp_path)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["fleet"]["ready"] == 3  # the fleet block surfaces

    (tmp_path / "heartbeat.json").write_text(json.dumps(
        {"time": time.time(), "step": 30, "wedged": False,
         "fleet_replicas": 3, "fleet_ready": 3, "fleet_evictions": 2,
         "fleet_respawns": 2, "fleet_broken": 0, "fleet_failovers": 5}))
    assert main(["tail", "--log-dir", str(tmp_path)]) == 4
    out = json.loads(capsys.readouterr().out)
    assert out["fleet"]["evictions"] == 2
    assert out["fleet"]["failovers"] == 5

    # a broken replica alone (no evictions counted) also exits 4
    (tmp_path / "heartbeat.json").write_text(json.dumps(
        {"time": time.time(), "step": 30, "wedged": False,
         "fleet_evictions": 0, "fleet_broken": 1}))
    assert main(["tail", "--log-dir", str(tmp_path)]) == 4
    capsys.readouterr()


# ------------------------------------------------ chaos (subprocess)


def _drive_load(port, bodies, total, clients, outcomes, stop=None):
    """Closed-loop client pool against the router; every request's
    outcome (status, payload) is recorded — the zero-silent-drops
    ledger."""
    import itertools

    counter = itertools.count()
    lock = threading.Lock()

    def worker():
        i = 0
        while True:
            n = next(counter)
            if n >= total or (stop is not None and stop.is_set()):
                return
            body = bodies[n % len(bodies)]
            try:
                status, payload = _post(port, body, timeout=30.0)
            except Exception as e:  # noqa: BLE001 - a drop would be a bug
                status, payload = -1, str(e).encode()
            with lock:
                outcomes.append((status, payload))
            i += 1

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    return outcomes


@pytest.mark.chaos
def test_fleet_chaos_crash_and_wedge_heal_via_failover(rng, tmp_path):
    """ISSUE 6 acceptance (fast tier, subprocess): 3 replicas under
    sustained load with a seeded SIGKILL on replica 0 and a seeded
    dispatch wedge on replica 1. The router replays failed requests on
    healthy siblings (>= 99% success), the supervisor evicts the sick
    replicas (the wedge via the serve heartbeat watchdog) and respawns
    them, every request resolves to a response or a structured error,
    and `tail` exits 4 surfacing the evictions."""
    from deepof_tpu.cli import main as cli_main
    from deepof_tpu.obs.heartbeat import Heartbeat

    fleet_dir = tmp_path / "fleet"
    cfg = _fleet_cfg(fleet_dir, max_batch=4, timeout_ms=5.0, exec_ms=5.0)
    cfg = cfg.replace(resilience=dataclasses.replace(
        cfg.resilience,
        faults=dataclasses.replace(cfg.resilience.faults, enabled=True,
                                   replica_crash_at=(0,),
                                   replica_wedge_at=(1,),
                                   replica_fault_after=40)))
    total, clients = 240, 6
    bodies = [_flow_body(rng) for _ in range(4)]
    outcomes: list = []
    with Fleet(cfg, 3) as fleet:
        fleet.start()
        fleet.wait_ready(min_ready=3, timeout_s=120)
        router, httpd, port = _start_router(cfg, fleet)
        # watchdog floored out of the way: this heartbeat only carries
        # the fleet_* block for tail (run_fleet's touch()es when idle;
        # here load simply stops, which must not read as a wedge)
        hb = Heartbeat(str(fleet_dir / "heartbeat.json"), period_s=0.1,
                       watchdog_min_s=3600.0,
                       sample=lambda: {**fleet.stats(), **router.stats()},
                       devmem=False)
        router.beat_hook = hb.beat
        try:
            _drive_load(port, bodies, total, clients, outcomes)
            # the wedged replica's eviction may trail the load (watchdog
            # window + poll): wait for the supervisor to finish healing
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                s = fleet.stats()
                if (s["fleet_crashes"] >= 1
                        and s["fleet_wedge_evictions"] >= 1
                        and s["fleet_respawns"] >= 1):
                    break
                time.sleep(0.2)
            stats = fleet.stats()
            time.sleep(0.3)  # one heartbeat period: the block lands
        finally:
            hb.close()
            router.draining = True
            httpd.shutdown()
            httpd.server_close()

    # zero silent drops: every submitted request resolved
    assert len(outcomes) == total
    ok = sum(1 for s, _ in outcomes if s == 200)
    failures = [(s, p[:200]) for s, p in outcomes if s != 200]
    # >= 99% success via failover (the injected faults kill in-flight
    # requests on 2 of 3 replicas; the router replays them)
    assert ok >= int(0.99 * total), (ok, total, failures[:5])
    for status, payload in failures:
        assert status > 0, f"transport-level silent failure: {payload}"
        assert b"error" in payload, (status, payload)

    # the supervisor observed and healed both failure modes
    assert stats["fleet_crashes"] >= 1, stats
    assert stats["fleet_wedge_evictions"] >= 1, stats
    assert stats["fleet_evictions"] >= 2, stats
    assert stats["fleet_respawns"] >= 1, stats
    assert stats["fleet_broken"] == 0, stats

    # the router actually failed over under the faults
    rstats = router.stats()
    assert rstats["fleet_failovers"] >= 1, rstats

    # the fleet heartbeat surfaces it and tail exits 4
    rc = cli_main(["tail", "--log-dir", str(fleet_dir)])
    assert rc == 4


@pytest.mark.chaos
def test_fleet_evicts_wedge_before_replica_watchdog_arms(rng, tmp_path):
    """A dispatch that hangs on the replica's FIRST flush wedges before
    its own watchdog can arm (3 completed flushes needed), and its
    heartbeat keeps rewriting fresh with wedged:false — the supervisor's
    stall detector (in-flight > 0 with no completion for
    fleet.stall_after_s) must evict it anyway, instead of leaving a
    permanent proxy-timeout tarpit on its affinity bucket."""
    fleet_dir = tmp_path / "fleet"
    cfg = _fleet_cfg(fleet_dir, stall_after_s=1.0, proxy_timeout_s=1.0)
    cfg = cfg.replace(resilience=dataclasses.replace(
        cfg.resilience,
        faults=dataclasses.replace(cfg.resilience.faults, enabled=True,
                                   replica_wedge_at=(0,),
                                   replica_fault_after=0)))
    outcomes: list = []
    with Fleet(cfg, 2) as fleet:
        fleet.start()
        fleet.wait_ready(min_ready=2, timeout_s=120)
        router, httpd, port = _start_router(cfg, fleet)
        try:
            bodies = [_flow_body(rng)]
            _drive_load(port, bodies, 24, 3, outcomes)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if fleet.stats()["fleet_wedge_evictions"] >= 1:
                    break
                time.sleep(0.1)
            stats = fleet.stats()
        finally:
            router.draining = True
            httpd.shutdown()
            httpd.server_close()
    # every request resolved, via failover to the healthy replica
    assert len(outcomes) == 24
    assert all(s == 200 for s, _ in outcomes), \
        [o for o in outcomes if o[0] != 200][:5]
    # evicted WITHOUT the replica's own watchdog ever flagging wedged
    assert stats["fleet_wedge_evictions"] >= 1, stats
    assert stats["fleet_evictions"] >= 1, stats


@pytest.mark.chaos
def test_fleet_circuit_breaker_stops_crash_loop(rng, tmp_path):
    """A replica that dies on its first dispatch every incarnation is
    respawned with backoff, then circuit-broken after
    crash_loop_threshold consecutive fast failures — instead of
    respawning forever — while the healthy replica keeps serving every
    request via failover.

    Deflaked for full-suite load (flaked once in PR 8's run): every
    deadline derives from the suite's shared `wait_for_listen` budget
    (one crash-loop incarnation is bounded by a spawn, which is bounded
    by that budget), the breaker-stays-open check observes for a
    backoff-derived window instead of a fixed 1 s sleep, and the drain/
    term grace is widened so a contended host cannot turn the healthy
    replica's clean SIGTERM exit into a SIGKILL escalation."""
    import inspect

    from deepof_tpu.serve.fleet import wait_for_listen as _wfl

    # the suite-wide per-spawn budget (conftest re-exports this default)
    listen_budget = float(
        inspect.signature(_wfl).parameters["timeout_s"].default)
    fleet_dir = tmp_path / "fleet"
    cfg = _fleet_cfg(fleet_dir, crash_loop_threshold=2, backoff_s=0.05,
                     backoff_max_s=0.2,
                     term_grace_s=listen_budget / 2,
                     drain_timeout_s=listen_budget / 2)
    cfg = cfg.replace(resilience=dataclasses.replace(
        cfg.resilience,
        faults=dataclasses.replace(cfg.resilience.faults, enabled=True,
                                   replica_crash_at=(0,),
                                   replica_fault_after=0)))
    # breaker trips after (threshold + 1) fast incarnations; each costs
    # at most one spawn window plus scheduling slack
    threshold = cfg.serve.fleet.crash_loop_threshold
    breaker_deadline_s = (threshold + 1) * 2 * listen_budget
    outcomes: list = []
    stop = threading.Event()
    with Fleet(cfg, 2) as fleet:
        fleet.start()
        fleet.wait_ready(min_ready=2, timeout_s=breaker_deadline_s)
        router, httpd, port = _start_router(cfg, fleet)
        bodies = [_flow_body(rng)]
        driver = threading.Thread(
            target=_drive_load,
            args=(port, bodies, 10_000, 2, outcomes, stop), daemon=True)
        driver.start()
        try:
            deadline = time.monotonic() + breaker_deadline_s
            while time.monotonic() < deadline:
                if fleet.stats()["fleet_broken"] >= 1:
                    break
                time.sleep(0.1)
            stop.set()
            driver.join(timeout=3 * listen_budget)
            stats = fleet.stats()
            # breaker open: replica 0 stays down, no more respawns
            assert stats["fleet_broken"] == 1, stats
            assert stats["fleet_states"]["replica-0"] == "broken", stats
            assert stats["fleet_crashes"] >= 2, stats
            assert stats["fleet_respawns"] >= 1, stats
            respawns_at_break = stats["fleet_respawns"]
            # service never went down: every request resolved, and the
            # healthy replica answers after the breaker opened
            assert outcomes and all(s == 200 for s, _ in outcomes), \
                [o for o in outcomes if o[0] != 200][:5]
            status, _ = _post(port, bodies[0])
            assert status == 200
            # breaker STAYS open: a still-looping replica would respawn
            # within backoff_max_s, so watching many backoff periods
            # (not one wall-clock second) is the honest negative check
            watch = time.monotonic() + max(
                10 * cfg.serve.fleet.backoff_max_s, 1.0)
            while time.monotonic() < watch:
                assert fleet.stats()["fleet_respawns"] == \
                    respawns_at_break
                time.sleep(cfg.serve.fleet.backoff_max_s / 2)
        finally:
            stop.set()
            httpd.shutdown()
            httpd.server_close()
    # graceful drain: the healthy replica exited cleanly on SIGTERM
    # (the widened term grace keeps this deterministic under suite load)
    assert fleet._replicas[1].last_exit == 0


# --------------------------------------------------- serve_bench fleet


def _load_serve_bench():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "serve_bench.py")
    spec = importlib.util.spec_from_file_location("serve_bench_fleet", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.chaos
def test_serve_bench_fleet_2x_replicas_beats_single(tmp_path):
    """ISSUE 6 acceptance: `serve_bench --fleet` with 2 healthy replicas
    sustains >= 1.5x a single replica's throughput through the full
    HTTP + router path. max_batch=1 + a per-dispatch sleep makes the
    fake executor latency-bound, so the win is genuine replica
    parallelism; the ratio gets one bounded retry against scheduler
    spikes on this 1-core host (schema asserted strictly every time)."""
    sb = _load_serve_bench()
    for attempt in range(2):
        # exec_ms dominates the per-request HTTP/router CPU cost on this
        # 1-core host, so the measured ratio stays near the ideal 2x
        res = sb.fleet_bench(replicas=2, requests=32, clients=6,
                             max_batch=1, timeout_ms=2.0, exec_ms=40.0,
                             log_dir=str(tmp_path / f"bench{attempt}"))
        for key in sb.FLEET_REQUIRED_KEYS:
            assert key in res, f"fleet_bench result missing {key!r}"
        json.dumps(res)  # JSON-line contract
        assert res["mode"] == "fleet" and res["replicas"] == 2
        assert res["errors"] == 0 and res["single_errors"] == 0
        assert res["shed"] == 0
        # both replicas actually served (the spill policy spreads a
        # saturated single-bucket load)
        assert len(res["routed"]) == 2, res["routed"]
        if res["speedup_vs_single"] >= 1.5:
            break
    assert res["speedup_vs_single"] >= 1.5, res
